"""Continuous-batching decode engine: one compiled step, rotating slots.

The offline path (:func:`distkeras_tpu.inference.generate.generate`)
decodes a *closed* batch: every row starts together, the whole batch runs
``max_new_tokens`` steps, stragglers pad out the scan. An online server
cannot do that — requests arrive whenever they arrive, and draining the
batch to admit one request wastes every other slot's compute.

This engine keeps the shape discipline that makes the offline path fast
(static ``[B_slots, max_seq_len, H, D]`` KV caches, ONE compiled decode
step for the lifetime of the server) while making the batch *open*:

- each of the ``slots`` rows of the decode batch is an independent
  request at its **own** sequence position (``BertConfig.decode_slots``
  turns the cache/positional indices into per-row vectors);
- a finished request frees its row; a queued request is admitted between
  decode iterations by a **prefill** program (compiled once per
  power-of-two prompt-length bucket) whose single-row KV cache is spliced
  into the live batch cache with ``dynamic_update_slice`` — the decode
  step itself never retraces and never stops for admission;
- with a **prefix cache** (``prefix_cache_mb``), the prompt's longest
  cached block-chain prefix is spliced from a device-resident pool
  (:mod:`distkeras_tpu.serving.prefix_cache`) instead of recomputed —
  only the uncached tail runs through the prefill program;
- with **chunked prefill** (``prefill_chunk``), that tail is split into
  fixed-size chunks and ONE chunk runs per engine iteration, interleaved
  with decode ticks — admitting a long prompt never stalls the decode
  batch for more than one chunk's device time, bounding every in-flight
  request's inter-token latency;
- free rows keep decoding garbage (their output is discarded) — the cost
  of a fixed-shape batch, and exactly the trade the training side makes
  with padded microbatches.

Per-request sampling: ``temperature <= 0`` rows take the argmax branch
inside the same compiled step (a ``jnp.where`` select, not a retrace), so
greedy and sampled requests coexist in one batch. ``top_k`` is
engine-wide static config.
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distkeras_tpu.inference.generate import (
    _check_context,
    _context_limit,
    _decode_module,
    _empty_cache,
    cache_with_index,
    sample_rows,
)
from distkeras_tpu.serving.metrics import ServingMetrics
from distkeras_tpu.serving.prefix_cache import PrefixCache
from distkeras_tpu.telemetry import RecompileAuditor, span
from distkeras_tpu.serving.scheduler import (
    EngineStopped,
    Request,
    RequestCancelled,
    RequestTimeout,
    Scheduler,
    ServingError,
)

__all__ = ["ServingEngine"]


def _prefill_fn(module, top_k, params, cache, padded, start, true_len, temp,
                key):
    """Run a right-padded ``[1, P]`` prompt *chunk* through the decode
    module at cache offset ``start``, extending the slot's KV cache and
    sampling the token that follows the chunk.

    ``start`` and ``true_len`` are traced scalars, so ONE compiled program
    serves every offset and every true length of a given pad width ``P``
    — monolithic prefill is the ``start == 0, P == bucket(prompt)`` case,
    a chunk of a longer prompt (or of the uncached tail after a
    prefix-cache splice) is the same program at a non-zero start.

    Padding is benign: causal attention means real positions never see the
    pad tail, the sampled token comes from the logits at ``true_len - 1``,
    and the garbage K/V at ``[start + true_len, start + P)`` is masked out
    of every later step (``k_pos <= q_pos``) until overwritten by real
    tokens. The index leaves are set to ``start`` on entry (so a
    prefix-cache splice never has to touch them) and rewound from
    ``start + P`` to ``start + true_len`` on exit so the next chunk — or
    decode — resumes at the real end.
    """
    cache = cache_with_index(cache, start)
    logits, mut = module.apply(
        {"params": params, "cache": cache}, padded, train=False,
        mutable=["cache"],
    )
    cache = cache_with_index(mut["cache"], start + true_len)
    last = jnp.take(logits[0], true_len - 1, axis=0)[None]  # [1, V]
    tok = sample_rows(last, temp[None], key, top_k)[0]
    return cache, tok


def _admit_fn(cache, tokens, temps, slot, pre_cache, first_tok, temp):
    """Splice a prefilled single-row cache into batch row ``slot``.

    ``slot`` is a traced scalar, so one compiled program serves every
    slot; every cache leaf carries the batch dim first in decode_slots
    mode, so the splice is a uniform leading-axis dynamic_update_slice.
    """
    cache = jax.tree.map(
        lambda big, small: lax.dynamic_update_slice(
            big, small.astype(big.dtype), (slot,) + (0,) * (small.ndim - 1)
        ),
        cache, pre_cache,
    )
    tokens = tokens.at[slot].set(first_tok)
    temps = temps.at[slot].set(temp)
    return cache, tokens, temps


def _decode_fn(module, top_k, params, cache, tokens, temps, key):
    """ONE decode iteration for the whole slot batch ``[B] -> [B]``."""
    logits, mut = module.apply(
        {"params": params, "cache": cache}, tokens[:, None], train=False,
        mutable=["cache"],
    )
    nxt = sample_rows(logits[:, -1], temps, key, top_k)
    return mut["cache"], nxt


@dataclasses.dataclass
class _PrefillJob:
    """Partial-prefill progress for a slot still being admitted: the
    single-row cache under construction, how far into the prompt it is
    (prefix-cache splice included), and the pinned match to release."""

    cache: object                 # single-row KV cache pytree
    pos: int                      # prompt tokens already in the cache
    match: object | None          # PrefixMatch to release on completion
    matched_tokens: int
    chunks_done: int = 0
    device_s: float = 0.0         # prefill device time (TTFT's other half)


@dataclasses.dataclass
class _SlotState:
    request: Request
    remaining: int  # tokens still to decode after the prefill token
    last_token_t: float
    # Non-None while the slot's prompt is still prefilling (chunked
    # admission): the row sits in the decode batch but its garbage output
    # is discarded until the finished cache is spliced in.
    prefill: _PrefillJob | None = None


class ServingEngine:
    """Fixed-slot continuous-batching server core.

    ``model``/``variables``: a causal LM from the zoo (gpt_tiny/gpt_small)
    and its trained variables — the same pair :func:`generate` takes.
    ``slots``: decode batch width (concurrent in-flight requests).
    ``max_queue``: admission backpressure depth (:class:`QueueFullError`
    beyond it). ``top_k``: engine-wide top-k sampling (None = full vocab).

    ``prefill_chunk``: split each prompt's (uncached) prefill into chunks
    of this many tokens, ONE chunk per engine iteration (round-robin
    across concurrently admitting slots) interleaved with decode ticks —
    bounds the decode stall (and thus every in-flight request's p99
    inter-token latency) by a single chunk's device time instead of a
    whole prompt's, regardless of how many prompts are admitting. None
    (default) keeps monolithic admission. Greedy output is
    token-identical either way.

    ``prefix_cache_mb``: > 0 enables the device-resident prefix cache
    (:class:`~distkeras_tpu.serving.prefix_cache.PrefixCache`) under that
    byte budget, with ``prefix_block_tokens``-token blocks: prompts
    sharing a cached prefix (system prompts, few-shot templates) splice
    the matched blocks instead of recomputing them, and the scheduler
    prefers cache-hitting requests within a priority class. Pass
    ``prefix_cache=`` to inject a pre-built pool (exact capacity
    control, test fixtures); the cache is NOT thread-safe — it must be
    driven by a single engine's loop at a time.

    Drive it with :meth:`submit` + :meth:`run` (asyncio); blocking device
    work (prefill, decode step) runs in the default executor so the event
    loop keeps accepting connections mid-decode.
    """

    def __init__(
        self,
        model,
        variables,
        *,
        slots: int = 4,
        max_queue: int = 64,
        top_k: int | None = None,
        metrics: ServingMetrics | None = None,
        seed: int = 0,
        min_prefill_bucket: int = 8,
        auditor: RecompileAuditor | None = None,
        arm_auditor_after_warmup: bool = False,
        prefill_chunk: int | None = None,
        prefix_cache_mb: float = 0.0,
        prefix_block_tokens: int = 16,
        prefix_cache: PrefixCache | None = None,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 or None, got {prefill_chunk}")
        self.model = model
        self._module, self._cfg = _decode_module(model, slots=True)
        if top_k is not None and not 1 <= top_k <= self._cfg.vocab_size:
            # Same bound generate() enforces: out-of-range top_k would
            # silently disable (or invert) the filtering via clamped
            # indexing rather than fail loudly.
            raise ValueError(
                f"top_k={top_k} outside [1, vocab_size={self._cfg.vocab_size}]"
            )
        self._params = variables["params"]
        self.limit = _context_limit(model, self._cfg)
        self.slots = int(slots)
        self.metrics = metrics or ServingMetrics()
        self.scheduler = Scheduler(max_depth=max_queue,
                                   registry=self.metrics.registry)
        self._min_bucket = int(min_prefill_bucket)
        self._chunk = None if prefill_chunk is None else int(prefill_chunk)
        self._prefill_rr = 0  # round-robin cursor over prefilling slots
        self._key = jax.random.PRNGKey(seed)

        # Device-resident batch state.
        self._cache = _empty_cache(self._module, self.slots)
        self._tokens = jnp.zeros((self.slots,), jnp.int32)
        self._temps = jnp.zeros((self.slots,), jnp.float32)
        self._slot_state: list[_SlotState | None] = [None] * self.slots

        # Single-row cache geometry, captured ONCE: eval_shape traces the
        # module's init, far too slow to re-run per admission. The zeroed
        # cache itself comes from ONE jitted factory (fused device-side
        # zeros, same cost profile as the zeros the prefill program used
        # to create in-jit) instead of a per-leaf host dispatch per
        # admission.
        self._row_shapes = jax.eval_shape(
            lambda r: self._module.init(
                r, jnp.zeros((1, 1), jnp.int32), train=False),
            jax.random.PRNGKey(0),
        )["cache"]
        self._fresh_row_cache = jax.jit(lambda: jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._row_shapes))

        # Prefix cache: a byte-budgeted pool of KV blocks shared across
        # requests (serving/prefix_cache.py). An explicit instance wins
        # (tests / multi-engine sharing); prefix_cache_mb > 0 builds one.
        if prefix_cache is not None:
            self.prefix_cache = prefix_cache
        elif prefix_cache_mb > 0:
            self.prefix_cache = PrefixCache(
                self._row_shapes, block_tokens=prefix_block_tokens,
                budget_bytes=int(prefix_cache_mb * 2**20),
                registry=self.metrics.registry)
        else:
            self.prefix_cache = None
        if self.prefix_cache is not None:
            # Cache-aware admission: the scheduler may prefer (within one
            # priority class, bounded window) the queued request whose
            # prefix is already resident — see Scheduler.pop.
            self.scheduler.cache_probe = self.prefix_cache.probe

        # One jit wrapper per engine so compile counts are per-instance:
        # the decode step must stay at exactly one executable for the
        # server's lifetime (see decode_compile_count()). The live batch
        # cache/tokens are donated — the engine rebinds them from each
        # call's outputs, and donation keeps the multi-MB KV caches
        # updating in place instead of copying per decoded token. _temps
        # is NOT donated in decode (it persists across iterations). The
        # prefill's incoming single-row cache is donated too: a chunk
        # chain threads one cache through every call, updating in place.
        self._prefill = jax.jit(
            functools.partial(_prefill_fn, self._module, top_k),
            donate_argnums=(1,))
        self._admit_jit = jax.jit(_admit_fn, donate_argnums=(0, 1, 2))
        self._decode_step = jax.jit(
            functools.partial(_decode_fn, self._module, top_k),
            donate_argnums=(1, 2))

        # Recompile auditing: the compile-count==1 decode invariant as a
        # RUNTIME check, not just a benchmark assertion. The auditor wraps
        # all three programs; with ``arm_auditor_after_warmup`` the decode
        # step is armed after its first iteration, so any later retrace
        # (admission, dtype drift) raises RecompileError at the offending
        # call instead of silently stretching tail latency.
        self.auditor = auditor
        self._arm_after_warmup = bool(arm_auditor_after_warmup)
        if auditor is not None:
            self._prefill = auditor.wrap(self._prefill, "serving_prefill")
            self._admit_jit = auditor.wrap(self._admit_jit, "serving_admit")
            self._decode_step = auditor.wrap(
                self._decode_step, "serving_decode")

        self._running = False
        self._stopping = False
        self._draining = True

    # -- introspection ------------------------------------------------------
    def decode_compile_count(self) -> int:
        """Number of compiled decode executables (must stay 1: admission
        must never retrace the decode step). -1 when the jit cache probe
        is unavailable; falls back to the auditor's count if one is
        attached (so audited engines keep a real count on jax versions
        without the private probe)."""
        probe = getattr(self._decode_step, "_cache_size", None)
        size = None
        if probe is not None:
            try:
                size = probe()
            except Exception:
                size = None
        if size is not None:
            return int(size)
        if self.auditor is not None:
            return self.auditor.compiles("serving_decode")
        return -1

    @property
    def active_slots(self) -> int:
        return sum(1 for s in self._slot_state if s is not None)

    @property
    def free_slots(self) -> int:
        return self.slots - self.active_slots

    # -- submission ---------------------------------------------------------
    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        priority: int = 0,
        timeout: float | None = None,
    ) -> Request:
        """Validate and enqueue a request; returns the streaming handle.

        Raises :class:`ValueError` (bad prompt / context overflow),
        :class:`QueueFullError` (backpressure), or :class:`EngineStopped`
        (shutting down) — all before any device work.
        """
        if self._stopping:
            raise EngineStopped("engine is shutting down; not admitting")
        prompt_arr = np.asarray(prompt, np.int32)
        if prompt_arr.ndim == 2 and prompt_arr.shape[0] == 1:
            prompt_arr = prompt_arr[0]
        if prompt_arr.ndim != 1 or prompt_arr.size < 1:
            raise ValueError(f"prompt must be a non-empty 1-D token list; "
                             f"got shape {prompt_arr.shape}")
        _check_context(self.model, self._cfg, prompt_arr[None, :],
                       max_new_tokens)
        req = Request(
            prompt_arr.tolist(), max_new_tokens, temperature=temperature,
            priority=priority, timeout=timeout,
        )
        try:
            self.scheduler.submit(req)
        except ServingError:
            self.metrics.record_reject()
            raise
        return req

    # -- lifecycle ----------------------------------------------------------
    def shutdown(self, drain: bool = True) -> None:
        """Stop admitting. ``drain=True`` finishes in-flight requests
        before :meth:`run` returns; ``drain=False`` errors them out."""
        self._stopping = True
        self._draining = drain
        self.scheduler.kick()

    def reopen(self) -> None:
        """Re-arm admission after a drain shutdown. The compiled programs
        and slot caches persist, so a bench can run several load phases on
        one engine without re-paying compilation."""
        if self._running:
            raise RuntimeError("cannot reopen while run() is active")
        self._stopping = False
        self._draining = True
        self.scheduler.reset_loop_state()

    async def run(self, idle_poll_s: float = 0.05) -> None:
        """Main loop: expire, admit, decode, stream — until shutdown."""
        if self._running:
            raise RuntimeError("engine.run() is already active")
        self._running = True
        loop = asyncio.get_running_loop()
        try:
            while True:
                now = time.monotonic()
                # 1. Shed queued requests that died waiting: deadline
                # passed, or caller cancelled (client disconnect).
                for req in self.scheduler.expire(now):
                    if req.cancelled:
                        self._finish_error(req, RequestCancelled(
                            "cancelled while queued"))
                    else:
                        self.metrics.record_expire()
                        self._finish_error(req, RequestTimeout(
                            f"deadline exceeded after {req.timeout}s in queue"))
                # 2. Free active slots whose request died mid-decode.
                for i, st in enumerate(self._slot_state):
                    if st is None:
                        continue
                    dl = st.request.deadline
                    if st.request.cancelled:
                        self._finish_error(st.request, RequestCancelled(
                            f"cancelled with {st.remaining} tokens undecoded"))
                        self._release_prefill(st)
                        self._slot_state[i] = None
                    elif dl is not None and now > dl:
                        self.metrics.record_expire()
                        self._finish_error(st.request, RequestTimeout(
                            f"deadline exceeded after {st.request.timeout}s "
                            f"with {st.remaining} tokens undecoded"))
                        self._release_prefill(st)
                        self._slot_state[i] = None
                # 3. Shutdown: flush the queue with typed errors.
                if self._stopping:
                    for req in self.scheduler.drain():
                        self._finish_error(
                            req, EngineStopped("engine shut down while queued"))
                # 4. Admission: prefill queued requests into free slots.
                # Device work runs in the executor; stream/metrics
                # bookkeeping stays on the loop thread (asyncio queues and
                # events are not thread-safe).
                if not self._stopping:
                    while self.free_slots and len(self.scheduler):
                        # Fresh clock per pop: an earlier admission's
                        # prefill may have taken long enough that more
                        # queued deadlines expired — a stale `now` would
                        # admit (and fully prefill) an already-dead
                        # request.
                        req = self.scheduler.pop(time.monotonic())
                        if req is None:
                            break
                        slot = self._slot_state.index(None)
                        # ADMISSION WAIT ends HERE (slot granted); the
                        # PREFILL DEVICE TIME is recorded separately when
                        # the prefill completes (record_prefill). The two
                        # series — plus chunk-interleave wait in chunked
                        # mode — make up TTFT, so an operator can tell
                        # queueing delay from prefill cost.
                        wait = time.monotonic() - req.t_submit
                        self.metrics.record_admit(wait)
                        st = _SlotState(req, req.max_new_tokens,
                                        time.monotonic())
                        self._slot_state[slot] = st
                        with span("admit", slot=slot,
                                  prompt_len=len(req.prompt),
                                  queue_wait_s=round(wait, 6)):
                            # Prefix-cache lookup + splice: a hit makes
                            # admission nearly free — the matched prefix's
                            # prefill compute is skipped entirely.
                            st.prefill = await self._in_executor(
                                loop, self._begin_prefill, req)
                            if self._chunk is None:
                                # Monolithic prefill: the whole uncached
                                # tail, admitted inline. Normally ONE
                                # call; near-context-limit prompts may
                                # split into a few pow2 sub-chunks (see
                                # _prefill_step's overshoot guard).
                                tok0 = None
                                while tok0 is None:
                                    tok0 = await self._in_executor(
                                        loop, self._prefill_step, st, slot)
                                self._finish_admission(st, slot, tok0)
                # 4b. Chunked prefill: ONE chunk per iteration TOTAL,
                # round-robin across prefilling slots, interleaved with
                # the decode tick below — the decode batch never stalls
                # for more than a single chunk's device time no matter
                # how many prompts are admitting at once (concurrent
                # admissions stretch each other's TTFT instead). Runs
                # during drain shutdown too (a half-prefilled slot must
                # finish for run() to exit).
                if self._chunk is not None:
                    pending = [i for i, st in enumerate(self._slot_state)
                               if st is not None and st.prefill is not None]
                    if pending:
                        start = self._prefill_rr
                        i = min(pending,
                                key=lambda s: (s - start) % self.slots)
                        self._prefill_rr = (i + 1) % self.slots
                        st = self._slot_state[i]
                        with span("prefill_tick", slot=i,
                                  offset=st.prefill.pos):
                            tok0 = await self._in_executor(
                                loop, self._prefill_step, st, i)
                        if tok0 is not None:
                            self._finish_admission(st, i, tok0)
                # 5. Nothing in flight?
                if self.active_slots == 0:
                    if self._stopping:
                        break
                    await self.scheduler.wait_for_request(idle_poll_s)
                    continue
                if self._stopping and not self._draining:
                    for i, st in enumerate(self._slot_state):
                        if st is not None:
                            self._finish_error(st.request, EngineStopped(
                                "engine shut down mid-decode"))
                            self._release_prefill(st)
                            self._slot_state[i] = None
                    break
                # 6. One decode iteration for the whole batch — skipped
                # while EVERY active slot is still mid-prefill (the whole
                # tick's output would be discarded; the chunk in 4b was
                # this iteration's useful device work).
                if any(st is not None and st.prefill is None
                       for st in self._slot_state):
                    with span("decode_tick", active=self.active_slots):
                        nxt = await self._in_executor(loop, self._decode_sync)
                    if self._arm_after_warmup and self.auditor is not None:
                        # First decode iteration IS the warmup: the one
                        # executable exists now, so every later compile is
                        # a violated invariant.
                        self._arm_after_warmup = False
                        self.auditor.arm("serving_decode")
                    t = time.monotonic()
                    with span("stream", active=self.active_slots):
                        for i, st in enumerate(self._slot_state):
                            if st is None or st.prefill is not None:
                                # Mid-prefill rows decode garbage until
                                # their finished cache is spliced in.
                                continue
                            self._push_token(st, int(nxt[i]), t)
                            if st.remaining == 0:
                                self._finish_ok(st.request)
                                self._slot_state[i] = None
                self.metrics.sample(
                    len(self.scheduler), self.active_slots, self.slots)
                # Yield so the server can read sockets between iterations.
                await asyncio.sleep(0)
        except BaseException as e:
            # A device failure — or the embedder cancelling the run()
            # task directly (CancelledError is a BaseException) — must
            # not strand clients: every in-flight and queued request gets
            # a terminal error event before the exception propagates
            # (otherwise server handlers block forever on streams nothing
            # will ever finish).
            err = ServingError(f"engine failure: {e!r}")
            for i, st in enumerate(self._slot_state):
                if st is not None:
                    self._finish_error(st.request, err)
                    self._release_prefill(st)
                    self._slot_state[i] = None
            for req in self.scheduler.drain():
                self._finish_error(req, err)
            self._stopping = True
            raise
        finally:
            self._running = False

    # -- internals ----------------------------------------------------------
    @staticmethod
    def _in_executor(loop, fn, *args):
        """run_in_executor with contextvars propagated (it doesn't, unlike
        asyncio.to_thread) so telemetry spans opened in the executor
        thread parent correctly to the loop-side span that dispatched
        them. copy_context() is copy-on-write — negligible per-call."""
        ctx = contextvars.copy_context()
        return loop.run_in_executor(None, lambda: ctx.run(fn, *args))

    def _bucket(self, n: int, cap: int | None = None) -> int:
        """Prefill pad length: next power of two >= n (>= min bucket),
        capped at the decodable context (and at ``cap`` — the chunk size,
        for a ragged final chunk) — bounds prefill compiles at
        log2(context) programs total."""
        b = self._min_bucket
        while b < n:
            b *= 2
        return min(b, self.limit if cap is None else min(cap, self.limit))

    def _release_prefill(self, st: _SlotState) -> None:
        """Drop a slot's pending prefill (cancel/expiry/shutdown paths):
        unpin its prefix-cache match so the blocks become evictable."""
        if st.prefill is not None:
            if self.prefix_cache is not None:
                self.prefix_cache.release(st.prefill.match)
            st.prefill = None

    def _finish_admission(self, st: _SlotState, slot: int, tok0: int) -> None:
        """Loop-thread bookkeeping once a slot's prefill completed: stream
        the first token (TTFT stamp) and free the slot if one token was
        all the request wanted."""
        t = time.monotonic()
        self._push_token(st, tok0, t, first=True)
        st.remaining -= 1
        if st.remaining == 0:
            self._finish_ok(st.request)
            self._slot_state[slot] = None

    def _begin_prefill(self, req: Request) -> _PrefillJob:
        """Start a prompt's prefill (executor thread): allocate the
        single-row cache and splice in the longest cached prefix — a hit
        skips that prefix's prefill compute entirely; the uncached tail
        runs through :meth:`_prefill_step` chunk by chunk."""
        cache = self._fresh_row_cache()
        match, matched = None, 0
        if self.prefix_cache is not None:
            match = self.prefix_cache.match(req.prompt)
            matched = match.matched_tokens
            if matched:
                with span("prefix_splice", blocks=len(match.ids),
                          tokens=matched):
                    cache = self.prefix_cache.splice(cache, match.ids)
        return _PrefillJob(cache=cache, pos=matched, match=match,
                           matched_tokens=matched)

    def _prefill_step(self, st: _SlotState, slot: int) -> int | None:
        """Run ONE prefill chunk for the slot (executor thread; device
        work only). Returns None while the prompt is still incomplete;
        on the final chunk, stores the prompt's new blocks into the
        prefix cache, splices the finished single-row cache into batch
        row ``slot``, and returns the request's first token."""
        req, job = st.request, st.prefill
        s0 = len(req.prompt)
        rem = s0 - job.pos
        c = rem if self._chunk is None else min(self._chunk, rem)
        if self._chunk is None:
            P = self._bucket(c)
        elif c == self._chunk:
            P = self._chunk  # full chunk: ONE fixed-size program
        else:
            P = self._bucket(c, cap=self._chunk)  # ragged final chunk
        # The pad width must never overshoot the cache: with job.pos + P
        # > max_seq_len the per-slot KV write would clamp its start
        # backward (bert.py's OOB discipline) and silently overwrite the
        # spliced prefix rows. Rather than compiling a bespoke
        # non-power-of-two width per matched length, shrink to the
        # largest power of two that fits and let the NEXT call(s) finish
        # the remainder — the compile set stays pow2-bounded and no
        # token is prefilled twice. (Monolithic admission loops on this
        # method until it returns a token, so near-context-limit prompts
        # just take an extra sub-chunk or two.)
        room = self._cfg.max_seq_len - job.pos
        if P > room:
            P = 1
            while P * 2 <= room:
                P *= 2
            c = min(c, P)  # room >= rem >= 1, so P >= 1 and c >= 1
        padded = np.zeros((1, P), np.int32)
        padded[0, :c] = req.prompt[job.pos:job.pos + c]
        self._key, sub = jax.random.split(self._key)
        temp = jnp.float32(req.temperature)
        t0 = time.monotonic()
        with span("prefill", bucket=P, offset=job.pos, prompt_len=s0):
            job.cache, tok = self._prefill(
                self._params, job.cache, jnp.asarray(padded),
                jnp.int32(job.pos), jnp.int32(c), temp, sub)
            tok0 = int(tok)  # blocks: honest device time per chunk
        job.device_s += time.monotonic() - t0
        job.chunks_done += 1
        job.pos += c
        if job.pos < s0:
            return None
        # Prompt complete. Store the complete blocks this prefill
        # computed (future requests sharing the prefix hit them), then
        # splice the row into the live batch cache.
        if self.prefix_cache is not None:
            with span("prefix_insert", prompt_len=s0):
                self.prefix_cache.insert(req.prompt, job.cache)
            self.prefix_cache.release(job.match)
        with span("cache_splice", slot=slot):
            self._cache, self._tokens, self._temps = self._admit_jit(
                self._cache, self._tokens, self._temps, jnp.int32(slot),
                job.cache, tok, temp)
        self.metrics.record_prefill(
            job.device_s, job.chunks_done,
            job.matched_tokens if self.prefix_cache is not None else None,
            s0)
        st.prefill = None
        return tok0

    def _decode_sync(self) -> np.ndarray:
        self._key, sub = jax.random.split(self._key)
        self._cache, self._tokens = self._decode_step(
            self._params, self._cache, self._tokens, self._temps, sub)
        return np.asarray(self._tokens)

    def _push_token(self, st: _SlotState, tok: int, t: float,
                    first: bool = False) -> None:
        req = st.request
        if first:
            req.t_first_token = t
            self.metrics.record_first_token(t - req.t_submit)
        else:
            self.metrics.record_inter_token(t - st.last_token_t)
            st.remaining -= 1
        st.last_token_t = t
        req.out_tokens.append(tok)
        req.events.put_nowait(("token", tok))

    def _finish_ok(self, req: Request) -> None:
        req.t_done = time.monotonic()
        self.metrics.record_finish(req.t_done - req.t_submit)
        req.events.put_nowait(("done", {
            "tokens": len(req.out_tokens),
            "ttft_s": req.ttft,
            "latency_s": req.t_done - req.t_submit,
        }))
        req.done.set()

    def _finish_error(self, req: Request, err: ServingError) -> None:
        req.error = err
        req.t_done = time.monotonic()
        req.events.put_nowait(("error", err))
        req.done.set()
