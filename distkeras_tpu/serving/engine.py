"""Continuous-batching decode engine: one compiled step, rotating slots.

The offline path (:func:`distkeras_tpu.inference.generate.generate`)
decodes a *closed* batch: every row starts together, the whole batch runs
``max_new_tokens`` steps, stragglers pad out the scan. An online server
cannot do that — requests arrive whenever they arrive, and draining the
batch to admit one request wastes every other slot's compute.

This engine keeps the shape discipline that makes the offline path fast
(static ``[B_slots, max_seq_len, H, D]`` KV caches, ONE compiled decode
step for the lifetime of the server) while making the batch *open*:

- each of the ``slots`` rows of the decode batch is an independent
  request at its **own** sequence position (``BertConfig.decode_slots``
  turns the cache/positional indices into per-row vectors);
- a finished request frees its row; a queued request is admitted between
  decode iterations by a **prefill** program (compiled once per
  power-of-two prompt-length bucket) whose single-row KV cache is spliced
  into the live batch cache with ``dynamic_update_slice`` — the decode
  step itself never retraces and never stops for admission;
- with a **prefix cache** (``prefix_cache_mb``), the prompt's longest
  cached block-chain prefix is spliced from a device-resident pool
  (:mod:`distkeras_tpu.serving.prefix_cache`) instead of recomputed —
  only the uncached tail runs through the prefill program;
- with **chunked prefill** (``prefill_chunk``), that tail is split into
  fixed-size chunks and ONE chunk runs per engine iteration, interleaved
  with decode ticks — admitting a long prompt never stalls the decode
  batch for more than one chunk's device time, bounding every in-flight
  request's inter-token latency;
- free rows keep decoding garbage (their output is discarded) — the cost
  of a fixed-shape batch, and exactly the trade the training side makes
  with padded microbatches.

**Paged KV mode** (``kv_pool_mb``/``paged``) replaces the dense
``[slots, L, H, D]`` per-slot cache — which pays worst-case length for
every slot up front — with ONE block pool shared by decode slots and the
prefix cache (:class:`~distkeras_tpu.serving.prefix_cache.KVBlockPool`):

- each slot's KV lives in fixed-size blocks addressed through a per-slot
  block table; the compiled decode step gathers K/V via traced table
  indices (:func:`distkeras_tpu.ops.attention.paged_attention`), so the
  single-compiled-decode-step invariant survives with paging on;
- capacity scales with *resident tokens*, not ``slots × max_seq_len`` —
  more concurrent slots per byte, and **long-context admission**:
  requests may use the model's whole trained context because blocks are
  chained on demand, never pre-reserved;
- prefix-cache hits are **zero-copy** (the table points at the shared
  ref-counted blocks) and a finished slot's blocks are **adopted** into
  the trie in place (zero-copy insert);
- the pool may be **oversubscribed**: when it runs dry, the engine
  preempts the lowest-priority youngest slot — its complete blocks are
  adopted (so re-admission re-matches them), the rest freed, and the
  request requeued at the front of its priority class
  (``Scheduler.requeue``); already-streamed tokens are folded into the
  resume prefill, so greedy output stays token-identical across the
  round trip. Requests whose full context can never fit are rejected
  with the typed ``kv_oom`` error at submit.

**Speculative decoding** (``draft_model``/``spec_k``) breaks the
one-full-model-dispatch-per-token latency floor: a small draft model
proposes K tokens per tick in ONE scanned dispatch, ONE batched target
call scores all K window positions per slot, and a masked accept
commits the longest verify-consistent DRAFT prefix — up to K tokens per
greedy row per tick, while ``temperature > 0`` rows ride the same tick
committing one sampled token from the verify's position-0 logits.
Committed values are always draft tokens (one-token-apply-computed,
the same lowering shape as ``generate()``'s scan), never tokens read
off the wide window's logits — the property that makes speculation
token-identical to ``generate()`` instead of almost-identical (see
``_spec_accept``). A row that accepts zero drafts is owed a one-token
fallback tick, so progress is unconditional. Everything is
shape-stable in K: rejected drafts roll back by rewinding the per-row
cache index leaves (dense) or by simply not advancing the host
``_lens`` watermark (paged, where the OOB-drop scatter already
guarantees an unallocated overhang cannot scribble) — so draft,
verify, and the one-token fallback each stay at exactly one compiled
executable under the armed ``RecompileAuditor``, variable acceptance
lengths and all.

Per-request sampling: ``temperature <= 0`` rows take the argmax branch
inside the same compiled step (a ``jnp.where`` select, not a retrace), so
greedy and sampled requests coexist in one batch. ``top_k`` is
engine-wide static config.

**Sharded serving** (``mesh``): hand the engine a
:func:`distkeras_tpu.parallel.mesh.serving_mesh` and ONE replica runs
the model GSPMD-sharded over the mesh's ``tp`` axis — models bigger
than one chip, served by the same engine:

- params are laid out per their logical-axis annotations
  (:func:`distkeras_tpu.parallel.sharding.infer_variable_shardings`) and
  **placed shard-then-place** (:func:`...gspmd.place_sharded`): each
  device receives only its slice, at boot and at every hot swap — the
  arXiv:2004.13336 move applied to weight rollout;
- the KV bytes — dense per-slot caches and the paged block pools alike
  — shard over the **heads** dimension
  (:func:`...sharding.kv_pytree_shardings`), while block tables, slot
  state, the scheduler, and every index stay replicated host metadata
  (the paged refactor is what makes this split clean: the pool's
  *meaning* was already host-side bookkeeping);
- every compiled callable — prefill, decode, draft, verify, fallback —
  is jitted with **explicit ``in_shardings``/``out_shardings``**, so
  layouts are pinned facts of each executable (stable across calls =
  still exactly ONE executable per callable under the armed auditor)
  rather than per-call propagation guesses;
- greedy output stays **token-identical** to the unsharded engine: the
  only tensor-parallel cross-device reductions (attention out-proj,
  mlp_out) keep float32 partial sums until after the all-reduce
  (``models.bert._F32AccumDense``), so layout noise stays far below the
  bf16 resolution ``greedy_ids`` quantizes to.

The draft model of a speculative engine stays **replicated** — it is
small by definition, and replicating it trades a little memory for zero
collectives in the latency-critical draft scan.

**Overlapped decode pipeline** (``pipeline_depth=1``, the default): the
run loop dispatches tick N+1 *before* consuming tick N's tokens. JAX
dispatch is asynchronous — the jit call returns as soon as the work is
enqueued — so the only point the host must wait for the device is the
one D2H per tick (``np.asarray`` on the tick's token vector, the
**harvest**). Serializing harvest right after dispatch (the old
``_decode_sync``) made the accelerator idle through the FULL host gap
between ticks: token streaming, slot teardown, admission bookkeeping,
scheduler/metrics work, and the event-loop turn that reads sockets.
Pipelined, all of that runs while the device executes the next tick:

- ``self._tokens`` stays a device array end to end and is **double
  buffered** — the decode step no longer donates its token operand, so
  dispatching tick N+1 never invalidates the buffer tick N's harvest
  is still going to read (16 bytes per tick of extra alloc, nothing);
- a **pipeline barrier** (harvest + stream + teardown of the in-flight
  tick) runs only at the events that change batch shape or content
  mid-flight: admission, chunked-prefill progress, paged growth /
  preemption, param swap (a swap still waits for zero in-flight
  ticks), KV transfer, cancel/expire teardown, and engine idle/exit;
- a slot that FINISHES at tick N is detected at N's harvest — after
  N+1 was dispatched, so the in-flight tick ran one speculative row
  for it. Its N+1 output is dropped exactly like a mid-prefill
  garbage row, and (paged) the host watermark advance the dispatch
  made for it is rolled back before teardown adopts its blocks, so
  pool accounting never claims the speculative in-flight write;
- speculative ticks dispatch asynchronously too, but the NEXT dispatch
  needs their commit counts (host-side position bookkeeping), so a
  spec tick is harvested before anything else is dispatched — spec
  mode hides the inter-iteration host gap (steps 1–4 + socket reads),
  while plain decode gets the full depth-1 overlap;
- greedy output is **token-identical** to ``pipeline_depth=0`` in
  every mode: the same ticks run in the same order over the same
  state, only the host's read of each tick's result is deferred.

Per-tick ``serving_host_gap_seconds`` / ``serving_device_idle_ratio``
(:class:`~distkeras_tpu.serving.metrics.HostGapTracker`) measure what
the pipeline hides, and :meth:`ServingEngine.tick_timeline` keeps a
bounded dispatch→harvest lane for tracez/debugz.
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import dataclasses
import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distkeras_tpu.inference.generate import (
    _check_context,
    _context_limit,
    _decode_module,
    _empty_cache,
    accept_prefix_length,
    cache_with_index,
    greedy_ids,
    sample_rows,
)
from distkeras_tpu.serving.metrics import ServingMetrics
from distkeras_tpu.serving.prefix_cache import KVBlockPool, PrefixCache
from distkeras_tpu.telemetry import (
    FlightRecorder,
    RecompileAuditor,
    TimelineRecord,
    TraceStore,
    WideEventStore,
    span,
)
from distkeras_tpu.serving.constraints import TokenDFA
from distkeras_tpu.serving.scheduler import (
    REQUEST_KINDS,
    SCORELIKE_KINDS,
    EngineStopped,
    PoolExhausted,
    Request,
    RequestCancelled,
    RequestTimeout,
    Scheduler,
    ServingError,
)

__all__ = ["ServingEngine"]


def _prefill_fn(module, top_k, params, cache, padded, start, true_len, temp,
                key):
    """Run a right-padded ``[1, P]`` prompt *chunk* through the decode
    module at cache offset ``start``, extending the slot's KV cache and
    sampling the token that follows the chunk.

    ``start`` and ``true_len`` are traced scalars, so ONE compiled program
    serves every offset and every true length of a given pad width ``P``
    — monolithic prefill is the ``start == 0, P == bucket(prompt)`` case,
    a chunk of a longer prompt (or of the uncached tail after a
    prefix-cache splice) is the same program at a non-zero start.

    Padding is benign: causal attention means real positions never see the
    pad tail, the sampled token comes from the logits at ``true_len - 1``,
    and the garbage K/V at ``[start + true_len, start + P)`` is masked out
    of every later step (``k_pos <= q_pos``) until overwritten by real
    tokens. The index leaves are set to ``start`` on entry (so a
    prefix-cache splice never has to touch them) and rewound from
    ``start + P`` to ``start + true_len`` on exit so the next chunk — or
    decode — resumes at the real end.
    """
    cache = cache_with_index(cache, start)
    logits, mut = module.apply(
        {"params": params, "cache": cache}, padded, train=False,
        mutable=["cache"],
    )
    cache = cache_with_index(mut["cache"], start + true_len)
    last = jnp.take(logits[0], true_len - 1, axis=0)[None]  # [1, V]
    tok = sample_rows(last, temp[None], key, top_k)[0]
    return cache, tok


def _admit_fn(cache, tokens, temps, slot, pre_cache, first_tok, temp):
    """Splice a prefilled single-row cache into batch row ``slot``.

    ``slot`` is a traced scalar, so one compiled program serves every
    slot; every cache leaf carries the batch dim first in decode_slots
    mode, so the splice is a uniform leading-axis dynamic_update_slice.
    """
    cache = jax.tree.map(
        lambda big, small: lax.dynamic_update_slice(
            big, small.astype(big.dtype), (slot,) + (0,) * (small.ndim - 1)
        ),
        cache, pre_cache,
    )
    tokens = tokens.at[slot].set(first_tok)
    temps = temps.at[slot].set(temp)
    return cache, tokens, temps


def _decode_fn(module, top_k, params, cache, tokens, temps, key):
    """ONE decode iteration for the whole slot batch ``[B] -> [B]``."""
    logits, mut = module.apply(
        {"params": params, "cache": cache}, tokens[:, None], train=False,
        mutable=["cache"],
    )
    nxt = sample_rows(logits[:, -1], temps, key, top_k)
    return mut["cache"], nxt


def _paged_prefill_fn(module, top_k, params, pools, padded, start, true_len,
                      table_row, temp, key):
    """Paged twin of :func:`_prefill_fn`: the chunk's K/V writes straight
    into the shared block pool through the slot's block table (no
    single-row scratch cache, no splice afterwards — admission IS the
    table row). ``start``/``true_len``/``table_row`` are traced, so ONE
    program serves every offset, true length, and block layout of a
    given pad width. Right-padded garbage past the table's allocated
    blocks is dropped by the scatter; garbage inside the tail block is
    masked (``k_pos <= q_pos``) until real tokens overwrite it — the
    same discipline as the dense path."""
    logits, mut = module.apply(
        {"params": params, "cache": pools}, padded, train=False,
        mutable=["cache"],
        positions=jnp.full((1,), start, jnp.int32),
        block_tables=table_row[None],
    )
    last = jnp.take(logits[0], true_len - 1, axis=0)[None]  # [1, V]
    tok = sample_rows(last, temp[None], key, top_k)[0]
    return mut["cache"], tok


def _paged_admit_fn(tokens, temps, slot, tok, temp):
    """Paged admission epilogue: only the sampling state changes — the
    KV is already resident in the pool, so there is nothing to splice."""
    return tokens.at[slot].set(tok), temps.at[slot].set(temp)


def _paged_decode_fn(module, top_k, sentinel, params, pools, tokens, temps,
                     positions, tables, key):
    """Paged twin of :func:`_decode_fn`: K/V appends scatter into the
    pool at each row's (traced) position and attention gathers through
    the (traced) block tables — one compiled executable for every table
    layout, admission pattern, and context length, which is what keeps
    the armed ``RecompileAuditor`` silent while blocks chain, slots are
    preempted, and long contexts grow.

    Positions advance DEVICE-SIDE: each row that is live in the masked
    table view (first table entry not the sentinel — exactly the rows
    whose write lands) comes back at ``position + 1``, so steady-state
    ticks re-feed the returned vector instead of rebuilding and
    re-uploading a host array every tick. The host re-uploads from its
    ``_lens`` truth only when the dirty flag says the decodable set or
    a watermark changed — the same gating the block tables use."""
    logits, mut = module.apply(
        {"params": params, "cache": pools}, tokens[:, None], train=False,
        mutable=["cache"], positions=positions, block_tables=tables,
    )
    nxt = sample_rows(logits[:, -1], temps, key, top_k)
    live = (tables[:, 0] != sentinel).astype(positions.dtype)
    return mut["cache"], nxt, positions + live


def _paged_decode_masked_fn(module, top_k, sentinel, params, pools, tokens,
                            temps, positions, tables, mask, key):
    """Constrained twin of :func:`_paged_decode_fn`: a per-slot additive
    token mask ``[slots, V]`` (0 allowed, large-negative forbidden —
    :class:`TokenDFA.mask_row`) lands on the last-position logits BEFORE
    sampling, so a masked greedy row can only emit automaton-legal
    tokens. Unconstrained rows carry an all-zero mask row — the add is
    a no-op for them, which is what lets ONE executable serve mixed
    constrained/unconstrained batches (the compile-count==1 invariant
    is the same as the unmasked step's: the mask is a plain operand,
    re-uploaded host-side only under a dirty flag)."""
    logits, mut = module.apply(
        {"params": params, "cache": pools}, tokens[:, None], train=False,
        mutable=["cache"], positions=positions, block_tables=tables,
    )
    nxt = sample_rows(logits[:, -1] + mask, temps, key, top_k)
    live = (tables[:, 0] != sentinel).astype(positions.dtype)
    return mut["cache"], nxt, positions + live


def _paged_prefill_logits_fn(module, params, pools, padded, start, true_len,
                             table_row):
    """Final-chunk prefill that returns the LOGITS row instead of a
    sampled token: the fork fan-out samples n tokens from it
    (:func:`_fork_sample_fn`) and constrained admission masks it
    host-side before picking the first token. KV writes are identical
    to :func:`_paged_prefill_fn` — only the sampling epilogue moved to
    the caller."""
    logits, mut = module.apply(
        {"params": params, "cache": pools}, padded, train=False,
        mutable=["cache"],
        positions=jnp.full((1,), start, jnp.int32),
        block_tables=table_row[None],
    )
    last = jnp.take(logits[0], true_len - 1, axis=0)  # [V]
    return mut["cache"], last.astype(jnp.float32)


def _fork_sample_fn(top_k, logits, temps, key):
    """Sample ``n`` independent continuations from ONE prefill logits
    row (the n>1 fork fan-out): the row is broadcast to ``[n, V]`` and
    :func:`sample_rows` draws each fork's first token — categorical
    over a batch samples independently per row under a single key, so
    one dispatch seeds all n forks. Compiles once per distinct n
    (report-only audit, like the pow2 prefill buckets)."""
    n = temps.shape[0]
    rows = jnp.broadcast_to(logits[None, :], (n, logits.shape[0]))
    return sample_rows(rows, temps, key, top_k)


def _score_chunk_fn(module, params, pools, padded, start, true_len,
                    table_row, targets):
    """Scoring prefill chunk: same paged KV writes as
    :func:`_paged_prefill_fn`, but instead of sampling, return each
    chunk position's log-probability of its NEXT prompt token —
    ``picked[j] = log_softmax(logits[j])[targets[j]]`` where
    ``targets[j]`` is the prompt token at global position
    ``start + j + 1``. The host accumulates per chunk and drops the
    pad tail and the final position (nothing follows it)."""
    logits, mut = module.apply(
        {"params": params, "cache": pools}, padded, train=False,
        mutable=["cache"],
        positions=jnp.full((1,), start, jnp.int32),
        block_tables=table_row[None],
    )
    logp = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, targets[:, None], axis=1)[:, 0]
    return mut["cache"], picked


def _embed_chunk_fn(module, params, pools, padded, start, true_len,
                    table_row):
    """Embedding prefill chunk: the trunk's raw hidden states
    (``return_hidden=True`` — pre-head, no extra params) summed over
    the chunk's TRUE positions (the right-pad tail is masked out). The
    host accumulates chunk sums and divides by the prompt length at
    completion — mean pooling without ever materializing ``[P, H]``
    host-side."""
    hidden, mut = module.apply(
        {"params": params, "cache": pools}, padded, train=False,
        mutable=["cache"], return_hidden=True,
        positions=jnp.full((1,), start, jnp.int32),
        block_tables=table_row[None],
    )
    valid = (jnp.arange(hidden.shape[1]) < true_len)[:, None]
    summed = jnp.sum(hidden[0].astype(jnp.float32) * valid, axis=0)
    return mut["cache"], summed


def _kv_gather_fn(cache, ids):
    """Gather pool rows ``ids`` from every KV leaf — the device half of
    a KV block EXPORT (:mod:`distkeras_tpu.serving.kv_transfer`).
    ``ids`` is pow2-padded so compiles stay bounded; the padding rows'
    garbage is sliced off host-side."""
    return jax.tree.map(lambda p: p[ids] if p.ndim > 1 else p[:0], cache)


def _kv_scatter_fn(cache, data, ids):
    """Scatter imported block rows ``data`` into the pool at rows
    ``ids`` — the device half of a KV block IMPORT. ``ids`` pads its
    pow2 bucket with out-of-range ids; ``mode="drop"`` discards those
    writes (the same OOB discipline as the pool's store path). Donates
    the pool."""
    return jax.tree.map(
        lambda p, d: (p.at[ids].set(d.astype(p.dtype), mode="drop")
                      if p.ndim > 1 else p),
        cache, data)


def _spec_draft_fn(module, K, params, cache, prev, tokens, start):
    """Fixed-K greedy draft scan: ONE dispatch proposes K tokens per row.

    ``start`` is the per-row fed-token count (int32 ``[B]``, the target's
    truth); setting the index leaves on entry is the draft-cache
    rollback — rejected draft K/V from the previous tick is simply
    overwritten as the new chain is fed, so no separate rewind pass
    exists. K is static (one compiled program per engine); the scan
    keeps the whole proposal at one device dispatch.

    The pass begins one position EARLY: a heal apply re-feeds ``prev``
    (the token at position ``start - 1``) before the K-step scan feeds
    ``tokens`` onward. Normally that rewrites K/V the draft already
    holds with the same values — but when the previous tick was a
    one-token FALLBACK (zero-accept row or speculate=False row in the
    batch), the target advanced past a position the draft never fed,
    and without the heal that hole would sit behind every later scan's
    attention forever, silently degrading the accept rate (measured:
    1.0 → ~0.79 in mixed traffic with draft == target). The run loop
    interleaves at most one fallback tick between spec ticks while an
    eligible row exists, so one healed position is always enough.

    Every step is a one-token apply — the SAME lowering shape as the
    offline ``generate()`` scan and the engine's fallback decode step.
    That is a correctness property, not an implementation detail: the
    engine commits DRAFT tokens (never tokens read off the wide verify
    window's logits), so with draft == target every committed token is
    bit-for-bit the sequential chain. Different-width lowerings of a
    bfloat16 trunk reorder its internal roundings and can flip argmax
    on near-ties; keeping all committed values one-token-shaped is what
    makes speculative output token-identical to ``generate()`` instead
    of merely almost-always-identical."""
    cache = cache_with_index(cache, jnp.maximum(start - 1, 0))
    _, mut = module.apply(
        {"params": params, "cache": cache}, prev[:, None], train=False,
        mutable=["cache"],
    )
    # The heal apply advanced the index leaves back to ``start`` (for
    # start == 0 rows the clamp makes it 1 — free/garbage rows only,
    # whose cache is rebuilt at admission).
    cache = cache_with_index(mut["cache"], start)

    def step(carry, _):
        cache, tok = carry
        logits, mut = module.apply(
            {"params": params, "cache": cache}, tok[:, None], train=False,
            mutable=["cache"],
        )
        nxt = greedy_ids(logits[:, -1].astype(jnp.float32))
        return (mut["cache"], nxt), nxt

    (cache, _), drafts = lax.scan(step, (cache, tokens), None, length=K)
    return cache, drafts.T  # [B, K]: d_1..d_K


def _spec_accept(logits, drafts, tokens, temps, spec_ok, remaining, key,
                 top_k):
    """Shared accept epilogue of both verify twins: from the target's
    ``[B, K, V]`` logits over the window ``[last_tok, d_1..d_{K-1}]``,
    decide how many DRAFT tokens each row commits.

    Two deliberate choices make this token-identical to ``generate()``
    instead of almost-identical:

    1. **Committed values are always the drafts themselves** — never
       tokens read off the window's logits. Drafts come from one-token
       applies (the same lowering shape as the sequential chain), while
       a K-wide window reorders the trunk's bfloat16 roundings: its
       argmax can flip on near-ties, so committing a window-derived
       "bonus" token (the textbook formulation) measurably diverged
       ~1/10^3 tokens on the random-init CI models.
    2. **The gate is ε-greedy at the model's compute precision**: draft
       ``d_{j+1}`` is accepted while its verify logit sits within ~2
       bfloat16 ULPs of the window's max — NOT on exact argmax
       equality, which the same cross-width noise spuriously breaks at
       ties (and a spurious reject routes the token through a fallback
       read of wide-written K/V, a coin toss at a tie site). Within the
       ε band the candidates are numerically indistinguishable at the
       precision the model itself computes in, so accepting the draft's
       choice IS greedy decoding. With draft == target this makes the
       committed chain bitwise the sequential chain, spurious-reject
       free; a genuinely wrong draft sits far below the band and is
       rejected as before. The relaxation's one caveat: a DIFFERENT
       draft that proposes the runner-up of an ε-tied pair commits it
       where sequential decode would pick the other member — output can
       then differ from ``generate()`` exactly at (and only at)
       positions the target itself scores as ties at its compute
       precision.

    A row that accepts zero drafts commits nothing this tick; the
    engine interleaves a one-token fallback tick so it always
    progresses. ``temperature > 0`` rows ride the same tick committing
    exactly one token sampled (shared :func:`sample_rows`) from offset
    0's logits — the distribution after ``last_tok``, i.e. what a plain
    decode tick sampled from. Greedy rows that OPTED OUT of speculation
    commit 0 here and are served by the fallback ticks their presence
    forces — their strict-parity promise must not route through wide
    logits. ``remaining`` clamps every row so a near-done request never
    overshoots ``max_new_tokens``.

    Returns ``(out, commit)``: ``out[b, :commit[b]]`` are row ``b``'s
    committed stream tokens."""
    logits = logits.astype(jnp.float32)
    tok0 = sample_rows(logits[:, 0], temps, key, top_k)
    eligible = spec_ok & (temps <= 0)
    top = jnp.max(logits, axis=-1)  # [B, K]
    drafted = jnp.take_along_axis(
        logits, drafts[..., None], axis=-1)[..., 0]  # [B, K]
    # ~2 bf16 ULPs of the max (floored for near-zero logits): far above
    # cross-width reduction noise (~1e-4 here), far below any decided
    # argmax gap.
    eps = jnp.float32(2**-7) * jnp.maximum(jnp.abs(top), 1.0)
    accepted = accept_prefix_length(drafted >= top - eps)
    commit = jnp.where(
        eligible, jnp.minimum(accepted, remaining),
        jnp.where(temps > 0, jnp.minimum(1, remaining), 0))
    out = jnp.concatenate(
        [jnp.where(eligible, drafts[:, 0], tok0)[:, None], drafts[:, 1:]],
        axis=1)
    return out, commit


def _spec_verify_fn(module, top_k, params, cache, tokens, drafts, temps,
                    spec_ok, remaining, positions, key):
    """Dense speculative verify: ONE target-model call scores all K
    window positions (``[last_tok, d_1..d_{K-1}]``) per slot, a masked
    accept commits the longest verify-consistent draft prefix, and the
    rejected tail is rolled back by rewinding the per-row cache index
    leaves (``cache_with_index`` with a per-row vector — the same
    offset-rewind contract chunked prefill uses). Everything is
    shape-stable in K, so variable acceptance lengths never retrace.

    Returns ``(cache, new_tokens, out, commit)``: ``out[b, :commit[b]]``
    are row ``b``'s committed stream tokens and ``new_tokens[b]`` its
    next feed token (the last committed one; unchanged on a zero
    commit, so re-running the tick is idempotent)."""
    window = jnp.concatenate([tokens[:, None], drafts[:, :-1]], axis=1)
    cache = cache_with_index(cache, positions)
    logits, mut = module.apply(
        {"params": params, "cache": cache}, window, train=False,
        mutable=["cache"],
    )
    out, commit = _spec_accept(logits, drafts, tokens, temps, spec_ok,
                               remaining, key, top_k)
    # Rollback: fed tokens end at positions + commit; the garbage K/V at
    # [positions + commit, positions + K) stays masked (k_pos <= q_pos)
    # until real tokens overwrite it — prefill's right-pad rule.
    cache = cache_with_index(mut["cache"], positions + commit)
    new_tok = jnp.where(
        commit > 0,
        jnp.take_along_axis(
            out, jnp.maximum(commit - 1, 0)[:, None], axis=1)[:, 0],
        tokens)
    return cache, new_tok, out, commit


def _paged_spec_verify_fn(module, top_k, params, pools, tokens, drafts,
                          temps, spec_ok, remaining, room, positions,
                          tables, key):
    """Paged twin of :func:`_spec_verify_fn`: the window's K/V scatters
    through the block tables (writes past a row's allocated blocks are
    dropped by ``paged_kv_update``), so ``room`` — the contiguous
    allocated positions from each row's write offset, computed host-side
    — additionally clamps the commit: a row whose lookahead blocks could
    not be allocated under pool pressure commits fewer tokens instead of
    committing tokens whose K/V was dropped. Rollback is the caller NOT
    advancing ``_lens`` past the commit; no device state to rewind."""
    window = jnp.concatenate([tokens[:, None], drafts[:, :-1]], axis=1)
    logits, mut = module.apply(
        {"params": params, "cache": pools}, window, train=False,
        mutable=["cache"], positions=positions, block_tables=tables,
    )
    out, commit = _spec_accept(logits, drafts, tokens, temps, spec_ok,
                               remaining, key, top_k)
    commit = jnp.minimum(commit, room)
    new_tok = jnp.where(
        commit > 0,
        jnp.take_along_axis(
            out, jnp.maximum(commit - 1, 0)[:, None], axis=1)[:, 0],
        tokens)
    return mut["cache"], new_tok, out, commit


def _draft_prefill_fn(module, params, cache, padded, start, true_len):
    """Draft twin of :func:`_prefill_fn` minus the sampling epilogue:
    extend the draft's single-row cache with a right-padded prompt chunk
    at offset ``start`` and rewind the index leaves to the true end. The
    draft never samples — its proposals come from the decode-time scan —
    so prefill only has to materialize the prompt's K/V."""
    cache = cache_with_index(cache, start)
    _, mut = module.apply(
        {"params": params, "cache": cache}, padded, train=False,
        mutable=["cache"],
    )
    return cache_with_index(mut["cache"], start + true_len)


def _draft_admit_fn(cache, slot, pre_cache):
    """Splice a prefilled single-row draft cache into batch row ``slot``
    (the draft half of :func:`_admit_fn`; no sampling state to set)."""
    return jax.tree.map(
        lambda big, small: lax.dynamic_update_slice(
            big, small.astype(big.dtype), (slot,) + (0,) * (small.ndim - 1)
        ),
        cache, pre_cache,
    )


# -- pipeline-parallel stage programs ---------------------------------------
# Per-stage twins of the monolithic programs above, for a ``tp=N,pp=M``
# serving mesh: each stage's jit sees ONLY its own placed param/cache
# subtree (parallel/pp.StagePlan) and compiles against its own tp-only
# sub-mesh. A non-last stage returns the traced activation the next
# stage's jit consumes — jax transfers it between the stage device sets
# at dispatch, and because the activation is ALWAYS committed to the
# producing stage's layout the consuming jit keys one cache entry (jit
# entries key on actual argument placement, so source-consistency is
# what keeps compile-count==1 per stage). ``stage`` is the static
# ``(lo, hi, first, last)`` slice Bert.__call__ takes.

def _pp_prefill_fn(module, stage, params, cache, x, start, true_len):
    """Non-last-stage slice of :func:`_prefill_fn`: extend this stage's
    single-row cache with the chunk and hand the activation on. ``x`` is
    the padded ``[1, P]`` token chunk on stage 0, the previous stage's
    ``[1, P, H]`` activation after; the index-leaf entry/rewind contract
    is per stage (every stage owns its own layers' index leaves)."""
    cache = cache_with_index(cache, start)
    act, mut = module.apply(
        {"params": params, "cache": cache}, x, train=False,
        mutable=["cache"], stage=stage,
    )
    return cache_with_index(mut["cache"], start + true_len), act


def _pp_prefill_last_fn(module, stage, top_k, params, cache, act, start,
                        true_len, temp, key):
    """Last-stage slice of :func:`_prefill_fn`: trunk tail + head +
    the sampling epilogue."""
    cache = cache_with_index(cache, start)
    logits, mut = module.apply(
        {"params": params, "cache": cache}, act, train=False,
        mutable=["cache"], stage=stage,
    )
    cache = cache_with_index(mut["cache"], start + true_len)
    last = jnp.take(logits[0], true_len - 1, axis=0)[None]  # [1, V]
    tok = sample_rows(last, temp[None], key, top_k)[0]
    return cache, tok


def _pp_decode_fn(module, stage, params, cache, x):
    """Non-last-stage slice of :func:`_decode_fn` (``x`` is ``tokens[:,
    None]`` on stage 0, the previous activation after)."""
    act, mut = module.apply(
        {"params": params, "cache": cache}, x, train=False,
        mutable=["cache"], stage=stage,
    )
    return mut["cache"], act


def _pp_decode_last_fn(module, stage, top_k, params, cache, act, temps, key):
    """Last-stage slice of :func:`_decode_fn`: trunk tail + sampling."""
    logits, mut = module.apply(
        {"params": params, "cache": cache}, act, train=False,
        mutable=["cache"], stage=stage,
    )
    nxt = sample_rows(logits[:, -1], temps, key, top_k)
    return mut["cache"], nxt


def _pp_paged_prefill_fn(module, stage, params, pools, x, start, table_row):
    """Non-last-stage slice of :func:`_paged_prefill_fn` — this stage's
    layer K/V scatters into ITS pool shard through the (replicated-
    per-stage) table row."""
    act, mut = module.apply(
        {"params": params, "cache": pools}, x, train=False,
        mutable=["cache"],
        positions=jnp.full((1,), start, jnp.int32),
        block_tables=table_row[None], stage=stage,
    )
    return mut["cache"], act


def _pp_paged_prefill_last_fn(module, stage, top_k, params, pools, act,
                              start, true_len, table_row, temp, key):
    """Last-stage slice of :func:`_paged_prefill_fn`."""
    logits, mut = module.apply(
        {"params": params, "cache": pools}, act, train=False,
        mutable=["cache"],
        positions=jnp.full((1,), start, jnp.int32),
        block_tables=table_row[None], stage=stage,
    )
    last = jnp.take(logits[0], true_len - 1, axis=0)[None]  # [1, V]
    tok = sample_rows(last, temp[None], key, top_k)[0]
    return mut["cache"], tok


def _pp_paged_decode_fn(module, stage, sentinel, params, pools, x, positions,
                        tables):
    """Non-last-stage slice of :func:`_paged_decode_fn`. Every stage
    returns its own ``positions + live`` vector (the advance rule is
    pure table arithmetic, identical across stages), so each stage's
    steady-state tick re-feeds its OWN returned vector and no positions
    ever cross stages."""
    act, mut = module.apply(
        {"params": params, "cache": pools}, x, train=False,
        mutable=["cache"], positions=positions, block_tables=tables,
        stage=stage,
    )
    live = (tables[:, 0] != sentinel).astype(positions.dtype)
    return mut["cache"], act, positions + live


def _pp_paged_decode_last_fn(module, stage, top_k, sentinel, params, pools,
                             act, temps, positions, tables, key):
    """Last-stage slice of :func:`_paged_decode_fn`."""
    logits, mut = module.apply(
        {"params": params, "cache": pools}, act, train=False,
        mutable=["cache"], positions=positions, block_tables=tables,
        stage=stage,
    )
    nxt = sample_rows(logits[:, -1], temps, key, top_k)
    live = (tables[:, 0] != sentinel).astype(positions.dtype)
    return mut["cache"], nxt, positions + live


def _pp_verify_first_fn(module, stage, params, cache, tokens, drafts,
                        positions):
    """Stage-0 slice of :func:`_spec_verify_fn`: build the verify window
    and run this stage's layers over it. The index leaves are left at
    ``positions + K``; the rewind to ``positions + commit`` happens in
    :func:`_pp_index_rewind_fn` once the LAST stage has decided the
    commit (the commit is a device scalar vector — the rewind jit
    consumes it without a host sync)."""
    window = jnp.concatenate([tokens[:, None], drafts[:, :-1]], axis=1)
    cache = cache_with_index(cache, positions)
    act, mut = module.apply(
        {"params": params, "cache": cache}, window, train=False,
        mutable=["cache"], stage=stage,
    )
    return mut["cache"], act


def _pp_verify_fn(module, stage, params, cache, act, positions):
    """Middle-stage slice of :func:`_spec_verify_fn`."""
    cache = cache_with_index(cache, positions)
    act, mut = module.apply(
        {"params": params, "cache": cache}, act, train=False,
        mutable=["cache"], stage=stage,
    )
    return mut["cache"], act


def _pp_verify_last_fn(module, stage, top_k, params, cache, act, drafts,
                       tokens, temps, spec_ok, remaining, positions, key):
    """Last-stage slice of :func:`_spec_verify_fn`: head + accept +
    THIS stage's index rewind (earlier stages rewind via
    :func:`_pp_index_rewind_fn` with the returned commit)."""
    cache = cache_with_index(cache, positions)
    logits, mut = module.apply(
        {"params": params, "cache": cache}, act, train=False,
        mutable=["cache"], stage=stage,
    )
    out, commit = _spec_accept(logits, drafts, tokens, temps, spec_ok,
                               remaining, key, top_k)
    cache = cache_with_index(mut["cache"], positions + commit)
    new_tok = jnp.where(
        commit > 0,
        jnp.take_along_axis(
            out, jnp.maximum(commit - 1, 0)[:, None], axis=1)[:, 0],
        tokens)
    return cache, new_tok, out, commit


def _pp_index_rewind_fn(cache, positions, commit):
    """Roll a non-last stage's index leaves back from ``positions + K``
    to ``positions + commit`` after a verify — the per-stage half of the
    dense rollback contract."""
    return cache_with_index(cache, positions + commit)


def _pp_paged_verify_first_fn(module, stage, params, pools, tokens, drafts,
                              positions, tables):
    """Stage-0 slice of :func:`_paged_spec_verify_fn` (no index leaves —
    rollback is the host not advancing ``_lens``)."""
    window = jnp.concatenate([tokens[:, None], drafts[:, :-1]], axis=1)
    act, mut = module.apply(
        {"params": params, "cache": pools}, window, train=False,
        mutable=["cache"], positions=positions, block_tables=tables,
        stage=stage,
    )
    return mut["cache"], act


def _pp_paged_verify_fn(module, stage, params, pools, act, positions,
                        tables):
    """Middle-stage slice of :func:`_paged_spec_verify_fn`."""
    act, mut = module.apply(
        {"params": params, "cache": pools}, act, train=False,
        mutable=["cache"], positions=positions, block_tables=tables,
        stage=stage,
    )
    return mut["cache"], act


def _pp_paged_verify_last_fn(module, stage, top_k, params, pools, act,
                             drafts, tokens, temps, spec_ok, remaining,
                             room, positions, tables, key):
    """Last-stage slice of :func:`_paged_spec_verify_fn`."""
    logits, mut = module.apply(
        {"params": params, "cache": pools}, act, train=False,
        mutable=["cache"], positions=positions, block_tables=tables,
        stage=stage,
    )
    out, commit = _spec_accept(logits, drafts, tokens, temps, spec_ok,
                               remaining, key, top_k)
    commit = jnp.minimum(commit, room)
    new_tok = jnp.where(
        commit > 0,
        jnp.take_along_axis(
            out, jnp.maximum(commit - 1, 0)[:, None], axis=1)[:, 0],
        tokens)
    return mut["cache"], new_tok, out, commit


@dataclasses.dataclass
class _PrefillJob:
    """Partial-prefill progress for a slot still being admitted: the
    single-row cache under construction, how far into the prompt it is
    (prefix-cache splice included), and the pinned match to release."""

    cache: object                 # single-row KV cache pytree
    pos: int                      # prompt tokens already in the cache
    match: object | None          # PrefixMatch to release on completion
    matched_tokens: int
    chunks_done: int = 0
    device_s: float = 0.0         # prefill device time (TTFT's other half)


def _tick_ready(tick) -> bool:
    """True when every device buffer the tick's harvest will read has
    already materialized — the harvest is then a plain memcpy, cheaper
    run inline than through an executor round trip. Conservative on
    jax versions without ``Array.is_ready`` (False → thread hop)."""
    try:
        if tick.kind == "spec":
            return bool(tick.out.is_ready() and tick.commit.is_ready())
        return bool(tick.tokens.is_ready())
    except AttributeError:
        return False


@dataclasses.dataclass
class _InflightTick:
    """A dispatched-but-unharvested decode tick: the device handles the
    harvest will read, the decodable rows the dispatch covered (the
    stream targets — the slot table may gain or lose entries before the
    harvest, and a row must stream iff it was decodable AT DISPATCH and
    its slot is still alive), and — plain paged ticks — the slots whose
    host ``_lens`` watermark the dispatch optimistically advanced, so a
    teardown detected mid-flight can roll the advance back before
    adopting blocks. With ``pipeline_depth>1`` on a pp mesh each tick
    covers ONE slot micro-batch; ``mb``/``mb_start`` map its mb-local
    token vector back to global slot ids at stream time."""

    kind: str                     # "decode" | "spec"
    rows: tuple                   # decodable slots at dispatch
    t_dispatch: float
    tokens: object = None         # plain: device token vector to harvest
    out: object = None            # spec: device [B, K] committed tokens
    commit: object = None         # spec: device per-row commit counts
    caps: object = None           # spec: host per-row draft budgets
    advanced: set = dataclasses.field(default_factory=set)
    mb: int = 0                   # micro-batch index (pp depth>1)
    mb_start: int = 0             # first global slot id of the micro-batch


def _public_provenance(provenance: dict | None) -> dict:
    """The client-facing face of a weights stamp: version + digest
    ONLY. checkpoint.weights_provenance also carries the server-side
    file ``path`` (and trainer stamps arbitrary meta) — stamping that
    into every done line and trace would disclose the server's
    filesystem layout to remote clients."""
    if not provenance:
        return {"version": 0, "digest": None}
    return {"version": int(provenance.get("version") or 0),
            "digest": provenance.get("digest")}


@dataclasses.dataclass
class _SlotState:
    request: Request
    remaining: int  # tokens still to decode after the prefill token
    last_token_t: float
    # Non-None while the slot's prompt is still prefilling (chunked
    # admission): the row sits in the decode batch but its garbage output
    # is discarded until the finished cache is spliced in.
    prefill: _PrefillJob | None = None
    # Paged mode: when this slot was admitted (preemption prefers the
    # YOUNGEST victim — least sunk work thrown away), the private block
    # ids it owns (block indices first_block, first_block+1, ... of its
    # table), and the pinned shared-prefix match its table head points
    # at (released only at slot teardown — the pin is what stops
    # eviction from reallocating a block the decode step still reads).
    t_admit: float = 0.0
    blocks: list = dataclasses.field(default_factory=list)
    first_block: int = 0
    match: object | None = None
    # Speculative decoding: lifetime draft/accept counters for this
    # slot's request (the debugz accept-rate column and per-request
    # trace stamps).
    spec_drafted: int = 0
    spec_accepted: int = 0
    # Request-kind state. Fork rows (kind="sample"): which fork of the
    # shared request this slot is (None for every other kind), its
    # PRIVATE token stream (fork tokens are never streamed as events —
    # the DONE frame carries all n completions), and fork_wait marks a
    # child slot claimed at admission but not yet fanned out (excluded
    # from the decodable set until the parent prefill completes).
    fork_idx: int | None = None
    fork_tokens: list | None = None
    fork_wait: bool = False
    # Constrained decoding: the request's automaton and its current
    # state (advanced host-side per streamed token).
    dfa: object | None = None
    dfa_state: int = 0
    # Scoring/embedding accumulators (prefill-only kinds).
    score_acc: list | None = None
    embed_acc: object | None = None


# Sentinel returned by _prefill_step when a prefill-only (score/embed)
# request's prompt completed — the run loop routes it to
# _finish_scorelike instead of _finish_admission.
_SCORELIKE_DONE = object()


@dataclasses.dataclass
class _ForkReady:
    """Returned by _prefill_step when a fork parent's prompt completed:
    the n first tokens (one per fork) sampled from the final chunk's
    logits; the run loop fans the children out from here."""
    tokens: list


class ServingEngine:
    """Fixed-slot continuous-batching server core.

    ``model``/``variables``: a causal LM from the zoo (gpt_tiny/gpt_small)
    and its trained variables — the same pair :func:`generate` takes.
    ``slots``: decode batch width (concurrent in-flight requests).
    ``max_queue``: admission backpressure depth (:class:`QueueFullError`
    beyond it). ``top_k``: engine-wide top-k sampling (None = full vocab).

    ``prefill_chunk``: split each prompt's (uncached) prefill into chunks
    of this many tokens, ONE chunk per engine iteration (round-robin
    across concurrently admitting slots) interleaved with decode ticks —
    bounds the decode stall (and thus every in-flight request's p99
    inter-token latency) by a single chunk's device time instead of a
    whole prompt's, regardless of how many prompts are admitting. None
    (default) keeps monolithic admission. Greedy output is
    token-identical either way.

    ``prefix_cache_mb``: > 0 enables the device-resident prefix cache
    (:class:`~distkeras_tpu.serving.prefix_cache.PrefixCache`) under that
    byte budget, with ``prefix_block_tokens``-token blocks: prompts
    sharing a cached prefix (system prompts, few-shot templates) splice
    the matched blocks instead of recomputing them, and the scheduler
    prefers cache-hitting requests within a priority class. Pass
    ``prefix_cache=`` to inject a pre-built pool (exact capacity
    control, test fixtures); the cache is NOT thread-safe — it must be
    driven by a single engine's loop at a time.

    ``kv_pool_mb`` > 0 (or ``paged=True`` with ``kv_pool_blocks``)
    selects **paged KV**: slots allocate fixed-size blocks
    (``kv_block_tokens`` tokens) from ONE shared pool
    (:class:`~distkeras_tpu.serving.prefix_cache.KVBlockPool`) instead
    of a dense per-slot cache — see the module docstring for what that
    buys (capacity ∝ resident tokens, zero-copy prefix sharing,
    preempt-and-requeue oversubscription, long-context admission). In
    paged mode prefix caching is inherent (``prefix_cache_mb`` is
    subsumed by the pool budget; passing ``prefix_cache=`` is an error).

    ``max_context``: cap each request's context (prompt + new tokens)
    below the model's trained length. In DENSE mode this also shrinks
    the pre-reserved per-slot cache to ``max_context`` positions — the
    knob that makes a fixed KV byte budget an explicit trade between
    slots and padded max length (the trade paged mode removes).

    ``draft_model``/``draft_variables``/``spec_k``: speculative decoding
    (see the module docstring). The draft must share the target's vocab
    (proposals are target token ids) and keeps its own dense per-slot
    cache whatever the target's paging; the zoo pairs gpt_tiny (draft)
    with gpt_small (target). K trades draft work against acceptance:
    each tick costs one scanned draft dispatch (a heal apply + K
    proposal steps) + one K-wide verify and commits up to K tokens per
    greedy row. A request can opt out per-call
    (``submit(..., speculate=False)``); ``temperature > 0`` rows never
    speculate. Rolling weight reloads swap the TARGET's params only —
    the draft is engine-lifetime config, and a stale draft can only
    lower the accept rate, never change committed output.

    ``mesh``: a :func:`distkeras_tpu.parallel.mesh.serving_mesh` turns
    this ONE engine into a GSPMD tensor-parallel replica (see the
    module docstring): params laid out per their logical axes, KV
    leaves heads-sharded, tables/slot/scheduler state replicated host
    metadata, every compiled callable pinned to explicit in/out
    shardings. The model's ``num_heads``/``mlp_dim``/``vocab_size``
    must divide the mesh's ``tp`` axis (validated here, typed). Greedy
    output is token-identical to the unsharded engine; hot swaps place
    candidate weights shard-then-place (bytes/tp per device).

    Observability (all default-off; see :mod:`distkeras_tpu.telemetry`):
    ``trace_store`` keeps per-request timeline records queryable by
    trace_id (the ``tracez`` verb); ``flight_recorder`` keeps a bounded
    black box of recent timelines + engine state transitions, dumped as
    last words if the run loop dies; ``slo_s`` arms the latency SLO —
    a request finishing slower bumps ``serving_slo_violations_total``
    and (with a recorder) pins its full timeline as a slow exemplar.
    With all three off, per-request timelines are never built and the
    per-token path does no tracing work at all.

    Drive it with :meth:`submit` + :meth:`run` (asyncio); blocking device
    work (prefill, decode step) runs in the default executor so the event
    loop keeps accepting connections mid-decode.
    """

    def __init__(
        self,
        model,
        variables,
        *,
        slots: int = 4,
        max_queue: int = 64,
        top_k: int | None = None,
        metrics: ServingMetrics | None = None,
        seed: int = 0,
        min_prefill_bucket: int = 8,
        auditor: RecompileAuditor | None = None,
        arm_auditor_after_warmup: bool = False,
        prefill_chunk: int | None = None,
        prefix_cache_mb: float = 0.0,
        prefix_block_tokens: int = 16,
        prefix_cache: PrefixCache | None = None,
        paged: bool = False,
        kv_pool_mb: float = 0.0,
        kv_block_tokens: int = 16,
        kv_pool_blocks: int | None = None,
        kv_host_tier_mb: float = 0.0,
        kv_disk_tier_dir: str | None = None,
        kv_disk_tier_mb: float = 0.0,
        kv_tier_watermark: float = 0.8,
        max_context: int | None = None,
        draft_model=None,
        draft_variables=None,
        spec_k: int = 4,
        mesh=None,
        pipeline_depth: int = 1,
        trace_store: TraceStore | None = None,
        flight_recorder: FlightRecorder | None = None,
        wide_events: "WideEventStore | int | None" = 4096,
        slo_s: float | None = None,
        weight_version: dict | None = None,
        tenant_weights: dict | None = None,
        tenant_quotas: dict | None = None,
        quota_burst_s: float = 2.0,
        constrained: bool = False,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 or None, got {prefill_chunk}")
        if int(pipeline_depth) != pipeline_depth or pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be a non-negative int: 0 (serialized "
                f"dispatch+harvest), 1 (dispatch tick N+1 before consuming "
                f"tick N), or >1 (micro-batched ticks overlapping pipeline "
                f"stages; needs a pp mesh), got {pipeline_depth}")
        self.pipeline_depth = int(pipeline_depth)
        # Dispatched-but-unharvested ticks, oldest first (at most
        # max(1, pipeline_depth) deep), and a bounded dispatch->harvest
        # timeline (the tracez tick lane).
        self._inflight: collections.deque = collections.deque()
        self._tick_log: collections.deque = collections.deque(maxlen=256)
        # False until the first decode dispatch has run (and therefore
        # compiled): the FIRST dispatch goes through the executor so a
        # multi-second compile cannot freeze the event loop, every later
        # one runs inline on the loop thread — dispatch is non-blocking
        # by design (async jax dispatch), and the executor round trip
        # it used to pay per tick is pure overhead that, on small
        # models, can cost more than the host gap the pipeline hides.
        self._dispatch_warm = False
        self.model = model
        self._spec = draft_model is not None
        self.draft_model = draft_model
        self.spec_k = int(spec_k)
        if self._spec and self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if self._spec and draft_variables is None:
            raise ValueError("draft_model needs draft_variables (the draft's "
                             "trained weights)")
        self._paged = bool(paged or kv_pool_mb > 0 or kv_pool_blocks)
        # GSPMD-sharded serving: ONE replica spread over a device mesh's
        # "tp" axis. Validated up front — a bad mesh must be a typed
        # ValueError here, not a jax lowering error three layers down.
        self.mesh = mesh
        self._tp = 1
        self._pp = 1
        self._replicated = None
        self._param_shardings = None
        self._cache_shardings = None
        self._stage_plan = None
        self._stage_meshes = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from distkeras_tpu.parallel.mesh import pp_stages

            if "tp" not in mesh.axis_names:
                raise ValueError(
                    f"serving mesh {dict(mesh.shape)} has no 'tp' axis; "
                    f"build it with parallel.mesh.serving_mesh")
            self._tp = int(mesh.shape["tp"])
            self._pp = int(pp_stages(mesh))
            extra = {a: s for a, s in mesh.shape.items()
                     if a not in ("tp", "pp") and s > 1}
            if extra:
                raise ValueError(
                    f"serving mesh has non-trivial non-tp axes {extra}: "
                    f"data parallelism in serving is N replicas (run.py "
                    f"cluster), not a dp mesh axis inside one engine")
            self._replicated = NamedSharding(mesh, P())
        # Constrained (structured) decoding: the decode executable takes
        # a per-slot token-mask operand. Paged-only (the mask hook lives
        # in the paged decode step) and single-stage only (the mask
        # lands on the LAST stage's logits; threading it through the pp
        # chain is future work).
        self._constrained_mode = bool(constrained)
        if self._constrained_mode and not self._paged:
            raise ValueError(
                "constrained=True requires paged KV (kv_pool_mb / "
                "kv_pool_blocks): the token-mask hook lives in the paged "
                "decode step")
        if self._constrained_mode and self._pp > 1:
            raise ValueError(
                "constrained=True is not supported on a pp mesh yet")
        # Micro-batch geometry. pipeline_depth > 1 only buys overlap when
        # ticks flow through >1 stage (a single-stage device serializes
        # them anyway), so it requires a pp mesh; the slot batch is then
        # partitioned into max(1, depth) contiguous micro-batches, each
        # with at most one tick in flight at steady state.
        if self.pipeline_depth > 1:
            if self._pp < 2:
                raise ValueError(
                    f"pipeline_depth={self.pipeline_depth} needs a pp>=2 "
                    f"serving mesh (micro-batched ticks only overlap "
                    f"across pipeline stages; --mesh-shape tp=N,pp=M)")
            if self._spec:
                raise ValueError(
                    f"pipeline_depth={self.pipeline_depth} is incompatible "
                    f"with speculative decoding (draft/verify ticks span "
                    f"the whole slot batch); use pipeline_depth<=1")
            if slots % self.pipeline_depth:
                raise ValueError(
                    f"slots={slots} does not divide into pipeline_depth="
                    f"{self.pipeline_depth} equal micro-batches")
        self._mb_count = (max(1, self.pipeline_depth)
                          if self._pp > 1 else 1)
        self._mb_size = int(slots) // self._mb_count
        self._mb_rr = 0
        if self._pp > 1 and kv_host_tier_mb > 0:
            raise ValueError(
                "kv_host_tier_mb > 0 is not supported on a pp mesh yet: "
                "the host tier's gather/scatter programs span the whole "
                "pool, which is stage-partitioned under pp")
        # Geometry probe: the plain decode-slots config, for the trained
        # context limit and (paged) the per-token KV byte cost.
        base_module, base_cfg = _decode_module(model, slots=True)
        if mesh is not None and self._tp > 1:
            bad = [f"{name}={val}" for name, val in (
                ("num_heads", base_cfg.num_heads),
                ("mlp_dim", base_cfg.mlp_dim),
                ("vocab_size", base_cfg.vocab_size),
            ) if val % self._tp]
            if bad:
                raise ValueError(
                    f"model {getattr(model, 'name', model)!r} does not "
                    f"shard over tp={self._tp}: {', '.join(bad)} not "
                    f"divisible — pick a tp that divides all three")
        base_limit = _context_limit(model, base_cfg)
        if max_context is not None:
            if not 1 <= max_context <= base_limit:
                raise ValueError(
                    f"max_context={max_context} outside [1, trained "
                    f"context {base_limit}]")
            self.limit = int(max_context)
        else:
            self.limit = base_limit
        if self._paged:
            # Per-token KV byte cost from the UNCAPPED row geometry (the
            # paged module's own cache leaves are pool-shaped, not
            # row-shaped, so the budget math needs the dense twin).
            row_shapes = jax.eval_shape(
                lambda r: base_module.init(
                    r, jnp.zeros((1, 1), jnp.int32), train=False),
                jax.random.PRNGKey(0),
            )["cache"]
            if prefix_cache is not None:
                raise ValueError(
                    "paged mode subsumes the prefix cache (the KV pool "
                    "IS the prefix cache); do not pass prefix_cache=")
            if kv_block_tokens < 1:
                raise ValueError(
                    f"kv_block_tokens must be >= 1, got {kv_block_tokens}")
            bt = int(kv_block_tokens)
            table_blocks = -(-self.limit // bt)
            kv_leaves = [a for a in jax.tree.leaves(row_shapes)
                         if a.ndim > 1]
            bytes_per_block = sum(
                bt * int(np.prod(a.shape[2:])) * a.dtype.itemsize
                for a in kv_leaves)
            if kv_pool_blocks is not None:
                capacity = int(kv_pool_blocks)
            else:
                capacity = int(kv_pool_mb * 2**20) // bytes_per_block
            if capacity < 1:
                raise ValueError(
                    f"kv_pool_mb={kv_pool_mb} holds zero "
                    f"{bt}-token blocks (one block = {bytes_per_block} "
                    f"bytes)")
            self._module, self._cfg = _decode_module(
                model, slots=True, paged_blocks=capacity, page_tokens=bt,
                page_table_blocks=table_blocks, tp_mesh=mesh)
            # Prefill pad-width bound. NOT the table reach (table_blocks
            # * bt, which rounds UP past the context when bt doesn't
            # divide it): a pad width past max_seq_len would make the
            # positional dynamic_slice clamp BACKWARD and embed the
            # chunk's real tokens at wrong positions. submit() caps
            # every sequence at self.limit, so this loses nothing.
            self._cache_len = self.limit
            self.kv_block_tokens = bt
            self._table_blocks = table_blocks
            # Table sentinel: an id one past the pool marks "unallocated"
            # — paged_kv_update drops writes there, paged_attention masks
            # the reads.
            self._sentinel = capacity
        else:
            dense_len = (int(max_context) if max_context is not None
                         else base_cfg.max_seq_len)
            overrides = {}
            if max_context is not None or self._spec:
                # Speculative headroom: a verify window writes K+1 K/V
                # vectors starting at the row's fed count, which for a
                # request using its whole context reaches past the
                # request limit. Extending the CACHE (never the
                # positional table — params stay layout-identical) by
                # spec_k rows keeps those overhang writes from clamping
                # backward over real prefix K/V; the overhang itself is
                # rejected-draft garbage, rolled back by the index
                # rewind and masked until overwritten.
                overrides["decode_cache_len"] = dense_len + (
                    self.spec_k if self._spec else 0)
            self._module, self._cfg = _decode_module(
                model, slots=True, tp_mesh=mesh, **overrides)
            # Prefill pad-width bound: the REQUEST context, not the
            # spec-extended cache — prefill programs stay identical to a
            # non-speculating engine's.
            self._cache_len = dense_len
        if top_k is not None and not 1 <= top_k <= self._cfg.vocab_size:
            # Same bound generate() enforces: out-of-range top_k would
            # silently disable (or invert) the filtering via clamped
            # indexing rather than fail loudly.
            raise ValueError(
                f"top_k={top_k} outside [1, vocab_size={self._cfg.vocab_size}]"
            )
        if self._pp > 1:
            # Stage plan + per-stage modules. Each stage's module differs
            # from the engine's only in ``tp_mesh``: the sharding
            # constraints inside its compiled programs must name the
            # stage's OWN tp-only sub-mesh (a constraint against the full
            # tp×pp mesh would pin buffers to devices outside the
            # stage-jit's device set).
            from distkeras_tpu.parallel.mesh import stage_submesh
            from distkeras_tpu.parallel.pp import plan_stages

            self._stage_plan = plan_stages(self._cfg.num_layers, self._pp)
            self._stage_meshes = [stage_submesh(mesh, s)
                                  for s in range(self._pp)]
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._stage_rep = [NamedSharding(m, P())
                               for m in self._stage_meshes]
            self._stage_modules = [
                type(self._module)(
                    dataclasses.replace(self._cfg, tp_mesh=m))
                for m in self._stage_meshes]
        # Device-resident params from the start. An engine booted from a
        # weights FILE used to hold raw numpy leaves here — every jitted
        # dispatch re-converted them, and the first param swap (which
        # device_puts) then RETRACED the decode step: numpy and jax.Array
        # arguments occupy different jit-cache entries. One transfer at
        # construction makes boot and swap paths aval-identical.
        #
        # Sharded: the params' mesh layout comes from the model's
        # logical-axis annotations resolved against the mesh
        # (infer_variable_shardings), and boot goes through the SAME
        # shard-then-place seam every later hot swap uses — each device
        # is sent its slice directly, never a full replicated copy.
        if mesh is not None:
            from distkeras_tpu.parallel.sharding import (
                infer_variable_shardings,
                kv_pytree_shardings,
            )

            if self._pp > 1:
                # Per-stage shard-then-place: the abstract variables are
                # split along the stage plan and each stage's subtrees
                # resolve their logical axes against the stage's OWN
                # sub-mesh — so every param/KV leaf lands only on its
                # stage's devices, at boot and at every later hot swap.
                # The cache template is micro-batch-shaped: with
                # pipeline_depth>1 each micro-batch owns an independent
                # [mb_size, ...] cache tree per stage.
                abstract = jax.eval_shape(
                    lambda r: self._module.init(
                        r, jnp.zeros((self._mb_size, 1), jnp.int32),
                        train=False),
                    jax.random.PRNGKey(0))
                plan = self._stage_plan
                # Abstract UNSPLIT param template: what a reload's tree
                # must look like (request_param_swap validates against
                # this, never the per-stage list with its duplicated
                # tied embedding). Unboxed — the live params carry no
                # LogicallyPartitioned metadata, and re-hanging a
                # reload's leaves on a boxed treedef would retrace
                # every stage jit at the swap rewarm.
                from distkeras_tpu.parallel.sharding import unbox

                self._swap_template = jax.tree.flatten(
                    unbox(abstract["params"]))
                self._param_shardings = [
                    infer_variable_shardings(m, {"params": p})["params"]
                    for m, p in zip(self._stage_meshes,
                                    plan.split_params(abstract["params"]))]
                self._cache_shardings = [
                    kv_pytree_shardings(m, c)
                    for m, c in zip(self._stage_meshes,
                                    plan.split_tree(abstract["cache"]))]
            else:
                abstract = jax.eval_shape(
                    lambda r: self._module.init(
                        r, jnp.zeros((int(slots), 1), jnp.int32),
                        train=False),
                    jax.random.PRNGKey(0))
                self._param_shardings = infer_variable_shardings(
                    mesh, abstract)["params"]
                self._cache_shardings = kv_pytree_shardings(
                    mesh, abstract["cache"])
        from distkeras_tpu.parallel.gspmd import place_sharded

        if self._pp > 1:
            self._params = [
                place_sharded(part, sh)
                for part, sh in zip(
                    self._stage_plan.split_params(variables["params"]),
                    self._param_shardings)]
        else:
            self._params = place_sharded(variables["params"],
                                         self._param_shardings)
        self.slots = int(slots)
        self.metrics = metrics or ServingMetrics()
        self.scheduler = Scheduler(
            max_depth=max_queue,
            registry=self.metrics.registry,
            tenant_weights=tenant_weights,
            tenant_quotas=tenant_quotas,
            quota_burst_s=quota_burst_s,
            # ONE labeler across the scheduler's and the metrics'
            # tenant families: a tenant past the cardinality cap folds
            # into "__other__" consistently everywhere.
            tenant_labeler=getattr(self.metrics, "tenant_labeler", None))
        self._min_bucket = int(min_prefill_bucket)
        self._chunk = None if prefill_chunk is None else int(prefill_chunk)
        self._prefill_rr = 0  # round-robin cursor over prefilling slots
        self._key = jax.random.PRNGKey(seed)

        # Device-resident batch state. In paged mode ``_cache`` holds the
        # SHARED block pools (per-layer [capacity, bt, H, D] leaves, no
        # per-slot index leaves — positions/tables are passed per call);
        # in dense mode, the classic [slots, L, H, D] per-slot caches.
        # Sharded: the KV leaves are committed to their heads-sharded
        # layout at creation, and every compiled program's out_shardings
        # pins the same layout, so the bytes never migrate.
        if self._pp > 1:
            # Stage-partitioned state. ``_cache`` is a per-stage list —
            # paged: each stage's slice of the shared pools; dense: a
            # per-stage list of per-MICRO-BATCH [mb_size, ...] trees
            # (micro-batches must own disjoint device buffers so depth>1
            # ticks never contend for a donated cache). ``_tokens`` /
            # ``_temps`` are per-micro-batch vectors committed to the
            # LAST stage — where sampling produces and admission updates
            # them — so every feed of the stage-0 decode program carries
            # the same placement and its jit keys one cache entry.
            plan = self._stage_plan
            if self._paged:
                self._cache = [
                    jax.device_put(part, sh)
                    for part, sh in zip(
                        plan.split_tree(
                            _empty_cache(self._module, self.slots)),
                        self._cache_shardings)]
            else:
                mb_tree = plan.split_tree(
                    _empty_cache(self._module, self._mb_size))
                # Fresh zeros PER micro-batch: device_put of one shared
                # source tree can alias, and an aliased buffer donated
                # by micro-batch m's tick would be deleted out from
                # under micro-batch m+1's.
                self._cache = [
                    [jax.device_put(
                        jax.tree.map(
                            lambda a: jnp.zeros(a.shape, a.dtype), part),
                        sh)
                     for _ in range(self._mb_count)]
                    for part, sh in zip(mb_tree, self._cache_shardings)]
            rep_last = self._stage_rep[-1]
            self._tokens = [
                jax.device_put(jnp.zeros((self._mb_size,), jnp.int32),
                               rep_last)
                for _ in range(self._mb_count)]
            self._temps = [
                jax.device_put(jnp.zeros((self._mb_size,), jnp.float32),
                               rep_last)
                for _ in range(self._mb_count)]
        else:
            self._cache = _empty_cache(self._module, self.slots)
            self._tokens = jnp.zeros((self.slots,), jnp.int32)
            self._temps = jnp.zeros((self.slots,), jnp.float32)
            if mesh is not None:
                # Commit the rebound state to its layout NOW: jit cache
                # entries key on the actual argument shardings, so a
                # warmup or swap-rewarm tick on ctor-fresh (uncommitted)
                # tokens would occupy a DIFFERENT executable than every
                # post-admission tick on committed jit outputs — two
                # compiles of one program, which the armed auditor
                # rightly refuses.
                self._cache = jax.device_put(self._cache,
                                             self._cache_shardings)
                self._tokens = jax.device_put(self._tokens,
                                              self._replicated)
                self._temps = jax.device_put(self._temps, self._replicated)
        self._slot_state: list[_SlotState | None] = [None] * self.slots

        self.kv_pool: KVBlockPool | None = None
        self.kv_tier = None
        if self._paged:
            self.kv_pool = KVBlockPool(
                capacity, self.kv_block_tokens,
                bytes_per_block=bytes_per_block,
                registry=self.metrics.registry)
            # Host-side per-slot paging state: block tables (row i =
            # slot i's pool row per block index, sentinel = unallocated)
            # and written-KV lengths. The decode step gets (masked)
            # device views of these each tick.
            self._tables = np.full((self.slots, self._table_blocks),
                                   self._sentinel, np.int32)
            self._lens = np.zeros((self.slots,), np.int64)
            # Admission parking: when a pop'd request could not get
            # blocks (and nobody lower-priority was preemptible) it is
            # requeued at its class head and admission pauses until the
            # pool's version moves (a free, eviction-eligibility change,
            # or adoption) — re-matching the same head request every
            # iteration would only burn host time and skew hit stats.
            self._parked_at_version: int | None = None
            self._parked_req: Request | None = None
            # Device-side masked table cache with a DIRTY flag: the
            # masked view only changes when a table row mutates
            # (admission reserve / growth / preemption / teardown) or a
            # slot's decodable status flips (prefill completion) — each
            # of those sites sets the flag, and the per-tick upload is
            # skipped while it is clear. The flag replaces an
            # O(slots × blocks) np.array_equal compare that ran on
            # EVERY tick just to conclude "unchanged" bt-1 times out of
            # bt. (Positions still upload every tick — they advance
            # with each decoded token.)
            self._mark_tables_dirty()
            self._tables_dev = None
            # Device-side positions with the SAME dirty gating: the
            # decode step returns each live row's position + 1, so the
            # steady-state tick re-feeds the returned device vector and
            # the per-tick host build + H2D upload only happens when the
            # decodable set or a watermark actually changed (admission,
            # growth, preemption, teardown, prefill completion, spec
            # commits).
            self._positions_dev = None
            self._positions_dirty = True
            self.prefix_cache = None
            self.scheduler.cache_probe = self.kv_pool.probe
            # Host-RAM (optionally disk-backed) spill tier under the
            # pool: eviction victims spill D2H as exact KVX1 bytes and
            # re-admit H2D on a trie miss during admission — see
            # serving/kv_tier.py. The spill hook fires inside
            # _BlockTrie._alloc, which only runs on the engine loop (or
            # the executor while the loop awaits it) and always after a
            # pipeline barrier, so the gather never races a donated
            # in-flight tick.
            if kv_host_tier_mb > 0:
                from distkeras_tpu.serving.kv_tier import HostKVTier

                self.kv_tier = HostKVTier(
                    int(kv_host_tier_mb * 2**20), bt,
                    disk_dir=kv_disk_tier_dir,
                    disk_budget_bytes=int(kv_disk_tier_mb * 2**20),
                    watermark=kv_tier_watermark,
                    registry=self.metrics.registry)
                self.kv_pool.spill_hook = self._spill_block
                # Allocation BURSTS (a multi-block admission or import
                # evicting several victims at once) spill through the
                # batched hook: one D2H gather for the whole burst,
                # mirroring _readmit_from_tier's one-scatter H2D path.
                self.kv_pool.spill_many_hook = self._spill_blocks
            # Trace context for spill exemplars: the admission /
            # growth / import currently driving allocations.
            self._tier_trace_id: str | None = None
        else:
            # Single-row cache geometry, captured ONCE: eval_shape traces
            # the module's init, far too slow to re-run per admission.
            # Derived from the SERVING module (so a max_context cap is
            # reflected in the row length). The zeroed cache itself comes
            # from ONE jitted factory (fused device-side zeros) — only
            # paid on a prefix-cache MISS: a hit materializes its row
            # cache straight from the matched pool blocks
            # (PrefixCache.materialize), never building the covered
            # leaves as zeros first.
            self._row_shapes = jax.eval_shape(
                lambda r: self._module.init(
                    r, jnp.zeros((1, 1), jnp.int32), train=False),
                jax.random.PRNGKey(0),
            )["cache"]
            self._row_shardings = None
            if mesh is not None:
                from distkeras_tpu.parallel.sharding import (
                    kv_pytree_shardings,
                )

                if self._pp > 1:
                    # A "row cache" under pp is a per-stage LIST of
                    # single-row subtrees, each placed on its stage; the
                    # prefill chain, the admit splice, and the prefix
                    # cache all thread the list.
                    self._row_shapes = self._stage_plan.split_tree(
                        self._row_shapes)
                    self._row_shardings = [
                        kv_pytree_shardings(m, part)
                        for m, part in zip(self._stage_meshes,
                                           self._row_shapes)]
                else:
                    self._row_shardings = kv_pytree_shardings(
                        mesh, self._row_shapes)
            if self._pp > 1:
                fresh_jits = [
                    jax.jit(
                        functools.partial(
                            lambda shapes: jax.tree.map(
                                lambda s: jnp.zeros(s.shape, s.dtype),
                                shapes),
                            part),
                        out_shardings=sh)
                    for part, sh in zip(self._row_shapes,
                                        self._row_shardings)]
                self._fresh_row_cache = (
                    lambda jits=fresh_jits: [f() for f in jits])
            else:
                self._fresh_row_cache = jax.jit(
                    lambda: jax.tree.map(
                        lambda s: jnp.zeros(s.shape, s.dtype),
                        self._row_shapes),
                    **({} if mesh is None
                       else {"out_shardings": self._row_shardings}))

            # Prefix cache: a byte-budgeted pool of KV blocks shared
            # across requests (serving/prefix_cache.py). An explicit
            # instance wins (tests / multi-engine sharing);
            # prefix_cache_mb > 0 builds one. Sharded engines hand the
            # mesh down so the cache's device pools (and the rows its
            # materialize builds) live in the same heads-sharded layout
            # the batch cache does.
            if prefix_cache is not None:
                self.prefix_cache = prefix_cache
            elif prefix_cache_mb > 0:
                self.prefix_cache = PrefixCache(
                    self._row_shapes, block_tokens=prefix_block_tokens,
                    budget_bytes=int(prefix_cache_mb * 2**20),
                    registry=self.metrics.registry, mesh=mesh,
                    stage_meshes=self._stage_meshes)
            else:
                self.prefix_cache = None
            if self.prefix_cache is not None:
                # Cache-aware admission: the scheduler may prefer (within
                # one priority class, bounded window) the queued request
                # whose prefix is already resident — see Scheduler.pop.
                self.scheduler.cache_probe = self.prefix_cache.probe

        # Speculative decoding: a small draft model proposes spec_k
        # tokens per tick (one scanned dispatch), ONE batched target
        # call verifies all K+1 positions, and a masked accept commits
        # the longest greedy-consistent prefix — token-identical to
        # generate() by construction. The draft keeps its own DENSE
        # per-slot cache regardless of the target's paging (it is small
        # by definition — gpt_tiny drafting for gpt_small — so paying
        # worst-case length for it is noise next to the target pool).
        if self._spec:
            self._draft_module, self._draft_cfg = _decode_module(
                draft_model, slots=True,
                decode_cache_len=self.limit + self.spec_k)
            if self._draft_cfg.vocab_size != self._cfg.vocab_size:
                raise ValueError(
                    f"draft model vocab {self._draft_cfg.vocab_size} != "
                    f"target vocab {self._cfg.vocab_size}: draft proposals "
                    "must be target token ids")
            # Sharded engines REPLICATE the draft (params and cache):
            # the draft is small by definition — gpt_tiny drafting for
            # gpt_small — so replication buys a collective-free draft
            # scan on the latency-critical path for a memory cost that
            # is noise next to the sharded target. Under pp the draft
            # lives on STAGE 0's sub-mesh only (its proposals feed the
            # verify chain from the front).
            self._draft_rep = (self._stage_rep[0] if self._pp > 1
                               else self._replicated)
            self._draft_params = (
                jax.device_put(draft_variables["params"])
                if mesh is None else
                jax.device_put(draft_variables["params"], self._draft_rep))
            self._draft_cache = _empty_cache(self._draft_module, self.slots)
            if mesh is not None:
                self._draft_cache = jax.device_put(self._draft_cache,
                                                   self._draft_rep)
            self._draft_row_shapes = jax.eval_shape(
                lambda r: self._draft_module.init(
                    r, jnp.zeros((1, 1), jnp.int32), train=False),
                jax.random.PRNGKey(0),
            )["cache"]
            self._fresh_draft_row = jax.jit(
                lambda: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype),
                    self._draft_row_shapes),
                **({} if mesh is None
                   else {"out_shardings": self._draft_rep}))
            # Host-side fed-token counts (int32 [slots], DENSE mode):
            # the per-row position the draft's entry rewind and the
            # dense verify's index rewind both derive from. Paged mode
            # already tracks the same quantity as ``_lens`` and uses
            # that instead.
            self._spec_pos = np.zeros((self.slots,), np.int32)
            # Set when a live row accepted zero drafts: the next tick
            # runs the one-token fallback step (progress guarantee).
            self._spec_owe_fallback = False

        # One jit wrapper per engine so compile counts are per-instance:
        # the decode step must stay at exactly one executable for the
        # server's lifetime (see decode_compile_count()). The live batch
        # cache is donated — the engine rebinds it from each call's
        # outputs, and donation keeps the multi-MB KV caches updating in
        # place instead of copying per decoded token. The decode step's
        # TOKEN operand is deliberately NOT donated (unlike the cache):
        # that is the pipeline's double buffer — tick N's output tokens
        # are the harvest handle the host reads AFTER tick N+1 has been
        # dispatched with them as input, so the dispatch must not
        # invalidate the buffer ([slots] int32 — the extra copy per tick
        # is 4 bytes per slot). _temps is NOT donated either (it
        # persists across iterations). The
        # prefill's incoming cache (single-row scratch in dense mode, the
        # shared pools in paged mode) is donated too: a chunk chain
        # threads it through every call, updating in place.
        # Sharded engines jit every callable with EXPLICIT in_shardings/
        # out_shardings: params in their logical-axis layout, KV leaves
        # heads-sharded, every index/token/table operand replicated. The
        # pinned layouts are part of each executable's signature — stable
        # across calls, so "exactly one executable per callable" survives
        # the mesh — and out_shardings guarantees the rebind-from-output
        # state (cache, tokens) never drifts off its layout.
        def _sharded_jit(fn, in_sh, out_sh, donate):
            if self.mesh is None:
                return jax.jit(fn, donate_argnums=donate)
            return jax.jit(fn, in_shardings=in_sh, out_shardings=out_sh,
                           donate_argnums=donate)

        rep = self._replicated
        psh = self._param_shardings
        csh = self._cache_shardings
        if self._pp > 1:
            self._build_pp_programs(top_k, auditor)
        elif self._paged:
            self._prefill = _sharded_jit(
                functools.partial(_paged_prefill_fn, self._module, top_k),
                (psh, csh, rep, rep, rep, rep, rep, rep), (csh, rep),
                donate=(1,))
            self._admit_jit = _sharded_jit(
                _paged_admit_fn,
                (rep, rep, rep, rep, rep), (rep, rep), donate=(0, 1))
            if self._constrained_mode:
                # The engine's ONE decode executable IS the masked
                # variant: the mask is a plain [slots, V] operand
                # (all-zero rows for unconstrained slots), so mixed
                # batches share it and compile-count==1 holds.
                self._decode_step = _sharded_jit(
                    functools.partial(_paged_decode_masked_fn,
                                      self._module, top_k, self._sentinel),
                    (psh, csh, rep, rep, rep, rep, rep, rep),
                    (csh, rep, rep), donate=(1,))
            else:
                self._decode_step = _sharded_jit(
                    functools.partial(_paged_decode_fn, self._module,
                                      top_k, self._sentinel),
                    (psh, csh, rep, rep, rep, rep, rep), (csh, rep, rep),
                    donate=(1,))
            # Request-kind programs (PR 19). _prefill_logits is the
            # final-chunk prefill that hands the logits row back (fork
            # fan-out, constrained first token); the score/embed chunks
            # reuse the paged prefill's KV writes with a different
            # epilogue. All are control-path (report-only audit): they
            # run once per admission, never per tick.
            self._prefill_logits = _sharded_jit(
                functools.partial(_paged_prefill_logits_fn, self._module),
                (psh, csh, rep, rep, rep, rep), (csh, rep), donate=(1,))
            self._fork_sample = _sharded_jit(
                functools.partial(_fork_sample_fn, top_k),
                (rep, rep, rep), rep, donate=())
            self._score_chunk = _sharded_jit(
                functools.partial(_score_chunk_fn, self._module),
                (psh, csh, rep, rep, rep, rep, rep), (csh, rep),
                donate=(1,))
            self._embed_chunk = _sharded_jit(
                functools.partial(_embed_chunk_fn, self._module),
                (psh, csh, rep, rep, rep, rep), (csh, rep), donate=(1,))
            # Constrained-decoding mask state: host truth [slots, V]
            # (zero rows = unconstrained), device copy re-uploaded only
            # under the dirty flag — the same gating the block tables
            # use, with the upload timed into mask_upload_seconds.
            if self._constrained_mode:
                self._mask_host = np.zeros(
                    (int(slots), self._cfg.vocab_size), np.float32)
                self._mask_dev = None
                self._mask_dirty = True
            # KV block migration (serving/kv_transfer.py): gather rows
            # for an export (output replicated — it is host-fetched
            # immediately, and on a sharded engine the all-gather IS
            # the full-heads serialization contract), scatter imported
            # rows back in (upload replicated, pool keeps its
            # heads-sharded layout — the kv_pytree_shardings reshard
            # seam). Both run between ticks via the engine loop's
            # pending-op queue, so they can never race a donated cache.
            self._kv_gather = _sharded_jit(
                _kv_gather_fn, (csh, rep), rep, donate=())
            self._kv_scatter = _sharded_jit(
                _kv_scatter_fn, (csh, rep, rep), csh, donate=(0,))
            # Pending export/import operations, serviced by the run
            # loop between iterations: (kind, arg, event, result).
            self._pending_kv: list[tuple] = []
        else:
            rsh = self._row_shardings
            self._prefill = _sharded_jit(
                functools.partial(_prefill_fn, self._module, top_k),
                (psh, rsh, rep, rep, rep, rep, rep), (rsh, rep),
                donate=(1,))
            self._admit_jit = _sharded_jit(
                _admit_fn,
                (csh, rep, rep, rep, rsh, rep, rep), (csh, rep, rep),
                donate=(0, 1, 2))
            self._decode_step = _sharded_jit(
                functools.partial(_decode_fn, self._module, top_k),
                (psh, csh, rep, rep, rep), (csh, rep), donate=(1,))
        if self._spec and self._pp == 1:
            # Draft cache donated; tokens are NOT (the verify consumes
            # them right after). Verify donates cache + tokens exactly
            # like the fallback decode step it substitutes for. The
            # draft trio runs fully replicated on a sharded engine.
            self._draft_step = _sharded_jit(
                functools.partial(_spec_draft_fn, self._draft_module,
                                  self.spec_k),
                (rep, rep, rep, rep, rep), (rep, rep), donate=(1,))
            if self._paged:
                self._verify_step = _sharded_jit(
                    functools.partial(_paged_spec_verify_fn, self._module,
                                      top_k),
                    (psh, csh, rep, rep, rep, rep, rep, rep, rep, rep,
                     rep),
                    (csh, rep, rep, rep), donate=(1, 2))
            else:
                self._verify_step = _sharded_jit(
                    functools.partial(_spec_verify_fn, self._module,
                                      top_k),
                    (psh, csh, rep, rep, rep, rep, rep, rep, rep),
                    (csh, rep, rep, rep), donate=(1, 2))
            self._draft_prefill = _sharded_jit(
                functools.partial(_draft_prefill_fn, self._draft_module),
                (rep, rep, rep, rep, rep), rep, donate=(1,))
            self._draft_admit = _sharded_jit(
                _draft_admit_fn, (rep, rep, rep), rep, donate=(0,))

        # Recompile auditing: the compile-count==1 decode invariant as a
        # RUNTIME check, not just a benchmark assertion. The auditor wraps
        # all three programs; with ``arm_auditor_after_warmup`` the decode
        # step is armed after its first iteration, so any later retrace
        # (admission, dtype drift) raises RecompileError at the offending
        # call instead of silently stretching tail latency.
        self.auditor = auditor
        self._arm_after_warmup = bool(arm_auditor_after_warmup)
        if self._pp == 1:
            self._decode_audit_names = ["serving_decode"] + (
                ["serving_draft", "serving_verify"] if self._spec else [])
        if auditor is not None and self._pp == 1:
            self._prefill = auditor.wrap(self._prefill, "serving_prefill")
            self._admit_jit = auditor.wrap(self._admit_jit, "serving_admit")
            if self._paged:
                # Report-only (never armed): export/import are rare
                # control-path operations, but their compile counts
                # still belong in the audit report.
                self._kv_gather = auditor.wrap(
                    self._kv_gather, "serving_kv_gather")
                self._kv_scatter = auditor.wrap(
                    self._kv_scatter, "serving_kv_scatter")
                # Request-kind programs: report-only, like the pow2
                # prefill buckets — admission-path work, never per-tick.
                self._prefill_logits = auditor.wrap(
                    self._prefill_logits, "serving_prefill_logits")
                self._fork_sample = auditor.wrap(
                    self._fork_sample, "serving_fork_sample")
                self._score_chunk = auditor.wrap(
                    self._score_chunk, "serving_score_chunk")
                self._embed_chunk = auditor.wrap(
                    self._embed_chunk, "serving_embed_chunk")
            self._decode_step = auditor.wrap(
                self._decode_step, "serving_decode")
            if self._spec:
                self._draft_step = auditor.wrap(
                    self._draft_step, "serving_draft")
                self._verify_step = auditor.wrap(
                    self._verify_step, "serving_verify")
                self._draft_prefill = auditor.wrap(
                    self._draft_prefill, "serving_draft_prefill")
                self._draft_admit = auditor.wrap(
                    self._draft_admit, "serving_draft_admit")

        # Request tracing + flight recording. Timelines are built only
        # when at least one sink exists — with both off the per-request
        # cost is a None attribute and the per-token cost is zero.
        self.trace_store = trace_store
        self.flight_recorder = flight_recorder
        # Hop identity stamped into timeline records (a LocalReplica
        # factory overwrites it with the replica id — several engines
        # share one pid there).
        self.trace_source = (flight_recorder.source
                             if flight_recorder is not None
                             else f"pid:{os.getpid()}")
        # Fleet role for wide-event attribution ("monolithic" unless a
        # disaggregated launcher overwrites it, like trace_source).
        self.serve_role = "monolithic"
        self.slo_s = None if slo_s is None else float(slo_s)
        self._trace_requests = (trace_store is not None
                                or flight_recorder is not None)
        # Wide-event analytics: one flat record per FINISHED request
        # into a bounded columnar ring — default ON (unlike timelines)
        # because the whole cost is one append at done-time, never
        # per-token. An int is a capacity; 0/None disables.
        if isinstance(wide_events, WideEventStore):
            self.wide_events: WideEventStore | None = wide_events
        elif wide_events:
            self.wide_events = WideEventStore(int(wide_events))
        else:
            self.wide_events = None
        if (flight_recorder is not None
                and getattr(flight_recorder, "wide_events", None) is None):
            # Crash dumps carry the wide-event ring tail: the requests
            # the process served right before it died, even when no
            # timeline store was armed.
            flight_recorder.wide_events = self.wide_events
        if self.slo_s is not None:
            self.metrics.set_slo(self.slo_s)

        # Weight provenance: which checkpoint the live params came from
        # ({"version": int, "digest": str} — see
        # checkpoint.weights_provenance). Stamped into every request at
        # admission, every done line, healthz/metricsz/debugz; updated
        # by a successful param swap. An engine started on inline
        # variables gets version 0 / digest None — the field is ALWAYS
        # present so consumers never branch on its existence.
        self.weight_version = _public_provenance(weight_version)
        self.metrics.set_weight_version(self.weight_version)
        # Device-memory accounting: params bytes are fixed at
        # construction; KV-pool bytes come from the pool's capacity and
        # high-water mark at refresh time.
        self._params_bytes = sum(
            getattr(l, "nbytes", 0) for l in jax.tree.leaves(self._params))

        self._running = False
        self._stopping = False
        self._draining = True
        # Fault-injection knob (the SLO bench's breach phase, via the
        # ``inject_latency`` control verb): a host-side sleep per decode
        # iteration. Purely host-time — the device work and compiled
        # executables are untouched, so the armed auditor stays at one
        # compile — but every slot's real ITL/TTFT stretches by it.
        self.inject_decode_delay_s = 0.0
        # Pending parameter swap: (params, done-event, result dict) set by
        # request_param_swap(), consumed by the run loop at the first
        # iteration with no slot in flight.
        self._pending_swap: tuple | None = None

        if self._spec:
            # Warm ALL THREE spec-mode executables (fallback decode,
            # draft scan, verify) on the pristine all-free batch NOW:
            # the run loop arms the auditor after the first real tick,
            # and which path that tick takes depends on traffic — a
            # lazily-compiled fallback (or verify) would then count as a
            # post-arm retrace. Garbage-in, garbage-out is safe here for
            # the same reason free rows may decode garbage every tick.
            self._decode_sync()
            self._spec_sync()
            # Every tick executable exists now: run-loop dispatches can
            # go inline from the first iteration.
            self._dispatch_warm = True

    # -- pipeline-parallel program construction -----------------------------
    def _build_pp_programs(self, top_k, auditor) -> None:
        """Compile the per-stage serving programs for a ``tp=N,pp=M``
        mesh: each pipeline stage gets its OWN jits (prefill slice,
        decode slice, admit splice, spec verify slice) at explicit
        in/out shardings against the stage's sub-mesh, and thin host
        wrappers chain them under the monolithic call signatures the
        dispatch paths already use.

        Compile-count==1 per stage rests on SOURCE CONSISTENCY, not on
        trust in auto-transfers: a jit cache entry keys on each
        argument's actual committed placement, so every argument
        position must always ARRIVE placed the same way. The invariants
        here: tokens/temps always live on the LAST stage (ctor
        device_put, admit + decode out_shardings); paged positions/
        tables are device_put per stage once and then re-fed from that
        stage's own outputs; fresh host values (chunk offsets, split
        keys, slot ids) are uncommitted — placement-free — every call;
        and every value that CROSSES a stage boundary (the residual
        activation, last-stage tokens feeding stage 0, the commit
        vector feeding non-last rewinds) goes through
        :meth:`_to_stage` — jax auto-transfers only single-device
        arrays between 1-device stages, and a committed tp-sharded
        array fed to another stage's sub-mesh is a runtime placement
        error, so the handoff is placed explicitly. The target layout
        is the same every call, so each stage jit still keys exactly
        one cache entry.
        """
        S = self._pp
        last = S - 1
        plan = self._stage_plan
        mods = self._stage_modules
        psh = self._param_shardings
        csh = self._cache_shardings
        reps = self._stage_rep
        rep_last = reps[-1]
        hop = self._to_stage

        def sjit(fn, in_sh, out_sh, donate):
            return jax.jit(fn, in_shardings=in_sh, out_shardings=out_sh,
                           donate_argnums=donate)

        def wrap(fn, name):
            return fn if auditor is None else auditor.wrap(fn, name)

        if self._paged:
            sent = self._sentinel
            pf = [wrap(sjit(
                functools.partial(_pp_paged_prefill_fn, mods[s],
                                  plan.stage_arg(s)),
                (psh[s], csh[s], reps[s], reps[s], reps[s]),
                (csh[s], reps[s]), (1,)), f"serving_prefill_s{s}")
                for s in range(last)]
            pf_last = wrap(sjit(
                functools.partial(_pp_paged_prefill_last_fn, mods[last],
                                  plan.stage_arg(last), top_k),
                (psh[last], csh[last]) + (reps[last],) * 6,
                (csh[last], rep_last), (1,)), f"serving_prefill_s{last}")

            def prefill(params, pools, padded, start, true_len, table_row,
                        temp, key):
                pools = list(pools)
                x = padded
                for s in range(last):
                    if s:
                        x = hop(x, s)
                    pools[s], x = pf[s](params[s], pools[s], x, start,
                                        table_row)
                pools[last], tok = pf_last(params[last], pools[last],
                                           hop(x, last) if last else x,
                                           start, true_len, table_row,
                                           temp, key)
                return pools, tok

            self._prefill = prefill
            admit = wrap(sjit(_paged_admit_fn, (rep_last,) * 5,
                              (rep_last, rep_last), (0, 1)),
                         "serving_admit")

            def admit_wrap(tokens, temps, slot, tok, temp):
                mb, local = divmod(int(slot), self._mb_size)
                tokens, temps = list(tokens), list(temps)
                tokens[mb], temps[mb] = admit(
                    tokens[mb], temps[mb], jnp.int32(local), tok, temp)
                return tokens, temps

            self._admit_jit = admit_wrap
            self._decode_steps = [wrap(sjit(
                functools.partial(_pp_paged_decode_fn, mods[s],
                                  plan.stage_arg(s), sent),
                (psh[s], csh[s], reps[s], reps[s], reps[s]),
                (csh[s], reps[s], reps[s]), (1,)), f"serving_decode_s{s}")
                for s in range(last)]
            self._decode_steps.append(wrap(sjit(
                functools.partial(_pp_paged_decode_last_fn, mods[last],
                                  plan.stage_arg(last), top_k, sent),
                (psh[last], csh[last]) + (reps[last],) * 5,
                (csh[last], rep_last, reps[last]), (1,)),
                f"serving_decode_s{last}"))
            # KV export/import are gated off under pp (the gather/
            # scatter programs span the whole pool); the run loop still
            # drains this (always-empty) queue.
            self._pending_kv = []
        else:
            rsh = self._row_shardings
            pf = [wrap(sjit(
                functools.partial(_pp_prefill_fn, mods[s],
                                  plan.stage_arg(s)),
                (psh[s], rsh[s], reps[s], reps[s], reps[s]),
                (rsh[s], reps[s]), (1,)), f"serving_prefill_s{s}")
                for s in range(last)]
            pf_last = wrap(sjit(
                functools.partial(_pp_prefill_last_fn, mods[last],
                                  plan.stage_arg(last), top_k),
                (psh[last], rsh[last]) + (reps[last],) * 5,
                (rsh[last], rep_last), (1,)), f"serving_prefill_s{last}")

            def prefill(params, cache, padded, start, true_len, temp, key):
                cache = list(cache)
                x = padded
                for s in range(last):
                    if s:
                        x = hop(x, s)
                    cache[s], x = pf[s](params[s], cache[s], x, start,
                                        true_len)
                cache[last], tok = pf_last(params[last], cache[last],
                                           hop(x, last) if last else x,
                                           start, true_len, temp, key)
                return cache, tok

            self._prefill = prefill
            splice = [wrap(sjit(_draft_admit_fn,
                                (csh[s], reps[s], rsh[s]), csh[s], (0,)),
                           f"serving_admit_s{s}") for s in range(S)]
            sample_admit = wrap(sjit(_paged_admit_fn, (rep_last,) * 5,
                                     (rep_last, rep_last), (0, 1)),
                                "serving_admit")

            def admit_wrap(cache, tokens, temps, slot, pre_cache, tok,
                           temp):
                mb, local = divmod(int(slot), self._mb_size)
                loc = jnp.int32(local)
                cache = [list(c) for c in cache]
                for s in range(S):
                    cache[s][mb] = splice[s](cache[s][mb], loc,
                                             pre_cache[s])
                tokens, temps = list(tokens), list(temps)
                tokens[mb], temps[mb] = sample_admit(
                    tokens[mb], temps[mb], loc, tok, temp)
                return cache, tokens, temps

            self._admit_jit = admit_wrap
            self._decode_steps = [wrap(sjit(
                functools.partial(_pp_decode_fn, mods[s],
                                  plan.stage_arg(s)),
                (psh[s], csh[s], reps[s]), (csh[s], reps[s]), (1,)),
                f"serving_decode_s{s}") for s in range(last)]
            self._decode_steps.append(wrap(sjit(
                functools.partial(_pp_decode_last_fn, mods[last],
                                  plan.stage_arg(last), top_k),
                (psh[last], csh[last], reps[last], rep_last, reps[last]),
                (csh[last], rep_last), (1,)), f"serving_decode_s{last}"))
        self._decode_step = None  # per-stage under pp: _decode_steps
        self._decode_audit_names = [f"serving_decode_s{s}"
                                    for s in range(S)]

        if self._spec:
            rep0 = reps[0]
            draft = wrap(sjit(
                functools.partial(_spec_draft_fn, self._draft_module,
                                  self.spec_k),
                (rep0,) * 5, (rep0, rep0), (1,)), "serving_draft")

            def draft_step(dp, dc, prev, tokens, start):
                # ``tokens`` is the engine's per-micro-batch list (spec
                # forces mb_count==1); it lives on the LAST stage, the
                # draft runs on stage 0.
                return draft(dp, dc, prev, hop(tokens[0], 0), start)

            self._draft_step = draft_step
            self._draft_prefill = wrap(sjit(
                functools.partial(_draft_prefill_fn, self._draft_module),
                (rep0,) * 5, rep0, (1,)), "serving_draft_prefill")
            self._draft_admit = wrap(sjit(
                _draft_admit_fn, (rep0, rep0, rep0), rep0, (0,)),
                "serving_draft_admit")
            if self._paged:
                vf0 = wrap(sjit(
                    functools.partial(_pp_paged_verify_first_fn, mods[0],
                                      plan.stage_arg(0)),
                    (psh[0], csh[0]) + (reps[0],) * 4,
                    (csh[0], reps[0]), (1,)), "serving_verify_s0")
                vmid = [wrap(sjit(
                    functools.partial(_pp_paged_verify_fn, mods[s],
                                      plan.stage_arg(s)),
                    (psh[s], csh[s], reps[s], reps[s], reps[s]),
                    (csh[s], reps[s]), (1,)), f"serving_verify_s{s}")
                    for s in range(1, last)]
                vlast = wrap(sjit(
                    functools.partial(_pp_paged_verify_last_fn, mods[last],
                                      plan.stage_arg(last), top_k),
                    (psh[last], csh[last]) + (reps[last],) * 10,
                    (csh[last], rep_last, rep_last, rep_last), (1,)),
                    f"serving_verify_s{last}")

                def verify(params, pools, tokens, drafts, temps, spec_ok,
                           remaining, room, start, tables, key):
                    toks = tokens[0]
                    pools = list(pools)
                    pools[0], act = vf0(params[0], pools[0], hop(toks, 0),
                                        drafts, start, tables[0])
                    for s in range(1, last):
                        pools[s], act = vmid[s - 1](params[s], pools[s],
                                                    hop(act, s), start,
                                                    tables[s])
                    pools[last], new_tok, out, commit = vlast(
                        params[last], pools[last], hop(act, last),
                        hop(drafts, last), toks,
                        temps[0], spec_ok, remaining, room, start,
                        tables[last], key)
                    return pools, [new_tok], out, commit

                self._verify_step = verify
            else:
                vf0 = wrap(sjit(
                    functools.partial(_pp_verify_first_fn, mods[0],
                                      plan.stage_arg(0)),
                    (psh[0], csh[0], reps[0], reps[0], reps[0]),
                    (csh[0], reps[0]), (1,)), "serving_verify_s0")
                vmid = [wrap(sjit(
                    functools.partial(_pp_verify_fn, mods[s],
                                      plan.stage_arg(s)),
                    (psh[s], csh[s], reps[s], reps[s]),
                    (csh[s], reps[s]), (1,)), f"serving_verify_s{s}")
                    for s in range(1, last)]
                vlast = wrap(sjit(
                    functools.partial(_pp_verify_last_fn, mods[last],
                                      plan.stage_arg(last), top_k),
                    (psh[last], csh[last]) + (reps[last],) * 8,
                    (csh[last], rep_last, rep_last, rep_last), (1,)),
                    f"serving_verify_s{last}")
                rewind = [wrap(sjit(_pp_index_rewind_fn,
                                    (csh[s], reps[s], reps[s]), csh[s],
                                    (0,)), f"serving_verify_rewind_s{s}")
                          for s in range(last)]

                def verify(params, cache, tokens, drafts, temps, spec_ok,
                           remaining, start, key):
                    toks = tokens[0]
                    rows = [c[0] for c in cache]
                    rows[0], act = vf0(params[0], rows[0], hop(toks, 0),
                                       drafts, start)
                    for s in range(1, last):
                        rows[s], act = vmid[s - 1](params[s], rows[s],
                                                   hop(act, s), start)
                    rows[last], new_tok, out, commit = vlast(
                        params[last], rows[last], hop(act, last),
                        hop(drafts, last), toks,
                        temps[0], spec_ok, remaining, start, key)
                    # Non-last stages left their index leaves at
                    # positions + K; roll each back to the committed
                    # length with the DEVICE commit vector — no host
                    # sync on the dispatch path.
                    for s in range(last):
                        rows[s] = rewind[s](rows[s], start,
                                            hop(commit, s))
                    return [[c] for c in rows], [new_tok], out, commit

                self._verify_step = verify
            self._decode_audit_names += (
                ["serving_draft"]
                + [f"serving_verify_s{s}" for s in range(S)]
                + ([] if self._paged else
                   [f"serving_verify_rewind_s{s}" for s in range(last)]))

    # -- introspection ------------------------------------------------------
    def decode_compile_count(self) -> int:
        """Number of compiled decode executables (must stay 1: admission
        must never retrace the decode step). -1 when the jit cache probe
        is unavailable; falls back to the auditor's count if one is
        attached (so audited engines keep a real count on jax versions
        without the private probe). Under pp the invariant is per
        STAGE — this returns the max over stages (1 iff every stage
        compiled exactly once); :meth:`decode_compile_counts` has the
        per-stage vector."""
        if self._pp > 1:
            counts = self.decode_compile_counts()
            return -1 if any(c < 0 for c in counts) else max(counts)
        size = self._probe_cache_size(self._decode_step)
        if size is not None:
            return int(size)
        if self.auditor is not None:
            return self.auditor.compiles("serving_decode")
        return -1

    @staticmethod
    def _probe_cache_size(fn) -> int | None:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:
            return None

    def decode_compile_counts(self) -> list[int]:
        """Per-stage decode compile counts (pp engines; ``[count]`` for
        a single-stage engine) — the per-stage face of the
        compile-count==1 invariant."""
        if self._pp == 1:
            return [self.decode_compile_count()]
        counts = []
        for s, fn in enumerate(self._decode_steps):
            size = self._probe_cache_size(fn)
            if size is None and self.auditor is not None:
                size = self.auditor.compiles(f"serving_decode_s{s}")
            counts.append(-1 if size is None else int(size))
        return counts

    def tick_timeline(self, n: int | None = None) -> list[dict]:
        """The bounded dispatch→harvest tick lane (most recent last):
        per tick, its kind, dispatch/harvest stamps, how long the
        harvest blocked on the device, and the measured host gap — the
        tracez view of what the pipeline is (or is not) hiding."""
        log = list(self._tick_log)
        return log if n is None else log[-int(n):]

    def mesh_info(self) -> dict | None:
        """Static view of the engine's device mesh for healthz/debugz:
        axis sizes and the per-shard device names — None unsharded, so
        consumers (router rollups, the deploy controller's fleet verify)
        can tell a sharded replica from a plain one at a glance."""
        if self.mesh is None:
            return None
        from distkeras_tpu.telemetry.device import _device_name

        info = {
            "axes": {a: int(s) for a, s in self.mesh.shape.items()},
            "tp": self._tp,
            "pp": self._pp,
            "devices": [_device_name(d)
                        for d in self.mesh.devices.flatten()],
        }
        if self._pp > 1:
            # Per-stage attribution: devices, owned layer range, and
            # resident params/KV bytes — the fleet-verify view of where
            # each stage's share of the model actually landed.
            stages = []
            for s in range(self._pp):
                lo, hi = self._stage_plan.layer_range(s)
                stages.append({
                    "stage": s,
                    "layers": [lo, hi],
                    "devices": [_device_name(d) for d in
                                self._stage_meshes[s].devices.flatten()],
                    "params_bytes": sum(
                        getattr(l, "nbytes", 0)
                        for l in jax.tree.leaves(self._params[s])),
                    "kv_bytes": sum(
                        getattr(l, "nbytes", 0)
                        for l in jax.tree.leaves(self._cache[s])),
                })
            info["stages"] = stages
        return info

    def _bytes_by_device(self, tree) -> dict[str, int]:
        """Per-device resident bytes of a (possibly sharded) pytree —
        what makes a sharded engine's params/KV attributable per shard
        instead of one engine-wide number. Host metadata only (shard
        shapes), no device sync."""
        from distkeras_tpu.telemetry.device import _device_name

        out: dict[str, int] = {}
        for leaf in jax.tree.leaves(tree):
            shards = getattr(leaf, "addressable_shards", None)
            if shards is None:
                continue
            for s in shards:
                name = _device_name(s.device)
                out[name] = out.get(name, 0) + int(
                    np.prod(s.data.shape) * s.data.dtype.itemsize)
        return out

    def refresh_memory_metrics(self) -> list[dict]:
        """Probe per-device ``memory_stats()`` (typed sentinel — a
        backend without the API publishes ``available=0``, never a fake
        0 bytes), publish the gauges plus this engine's workload-side
        bytes (params, KV pool reserved/peak), and return the per-device
        rows for healthz. Sharded engines additionally publish the
        params/KV bytes PER MESH DEVICE (labeled gauges + per-row
        fields), so each shard's footprint is attributable. Host-only;
        called per metricsz/healthz scrape, never on the decode path."""
        from distkeras_tpu.telemetry.device import publish_memory_gauges

        kv_bytes = kv_peak = None
        if self.kv_pool is not None and self.kv_pool.bytes_per_block:
            kv_bytes = self.kv_pool.capacity * self.kv_pool.bytes_per_block
            kv_peak = (self.kv_pool.peak_blocks_used
                       * self.kv_pool.bytes_per_block)
            # Device-tier occupancy of the KV hierarchy (host/disk
            # gauges are kept live by the tier itself).
            self.metrics.set_kv_tier_resident_bytes(
                self.kv_pool.blocks_used * self.kv_pool.bytes_per_block)
        params_by_dev = kv_by_dev = None
        if self.mesh is not None:
            try:
                params_by_dev = self._bytes_by_device(self._params)
                # KV leaves live in the engine's cache pytree in BOTH
                # modes (paged pools and dense per-slot caches alike).
                kv_by_dev = self._bytes_by_device(self._cache)
            except Exception:
                params_by_dev = kv_by_dev = None
        try:
            mems = publish_memory_gauges(
                self.metrics.registry,
                params_bytes=self._params_bytes,
                kv_pool_bytes=kv_bytes,
                kv_pool_peak_bytes=kv_peak,
                params_bytes_by_device=params_by_dev,
                kv_bytes_by_device=kv_by_dev)
        except Exception:
            return []
        rows = [m.to_dict() for m in mems]
        if params_by_dev or kv_by_dev:
            for row in rows:
                dev = row.get("device")
                if params_by_dev and dev in params_by_dev:
                    row["params_bytes"] = params_by_dev[dev]
                if kv_by_dev and dev in kv_by_dev:
                    row["kv_bytes"] = kv_by_dev[dev]
        return rows

    def tenant_snapshot(self) -> dict:
        """Per-tenant QoS rollup for healthz/debugz — occupancy (active
        decode slots), queue depth, quota bucket state, over-quota shed
        counts, and lifetime completed/token counters — refreshing the
        labeled tenant gauges on the way (scrape-time, like the memory
        gauges: the triage page for "is one tenant starving the
        fleet")."""
        active: dict[str, int] = {}
        for st in self._slot_state:
            if st is not None:
                t = st.request.tenant
                active[t] = active.get(t, 0) + 1
        out = self.scheduler.tenant_stats()
        for tenant, n in active.items():
            out.setdefault(tenant, {"queued": 0})["active_slots"] = n
        for tenant, counts in self.metrics.tenant_counters().items():
            out.setdefault(tenant, {"queued": 0}).update(counts)
        self.metrics.set_tenant_active(active)
        return out

    @property
    def active_slots(self) -> int:
        return sum(1 for s in self._slot_state if s is not None)

    @property
    def free_slots(self) -> int:
        return self.slots - self.active_slots

    def debugz(self) -> dict:
        """Live state snapshot for the ``debugz`` control verb: the slot
        table (per-slot phase, trace_id, sequence depth, age), the
        scheduler queue with per-request ages, prefix-cache trie
        occupancy, and flight-recorder/SLO status — the "what is the
        engine doing RIGHT NOW" page metricsz's aggregates can't answer.
        JSON-safe; reads live structures without locking (the asyncio
        control handler and the engine loop interleave at await points,
        and a slightly torn read of a diagnostic page is harmless)."""
        now = time.monotonic()
        slots = []
        for i, st in enumerate(self._slot_state):
            if st is None:
                slots.append({"slot": i, "state": "free"})
                continue
            req = st.request
            entry = {
                "slot": i,
                "state": ("prefill" if st.prefill is not None
                          else "fork_wait" if st.fork_wait
                          else "decode"),
                "kind": req.kind,
                "trace_id": req.trace_id,
                "tenant": req.tenant,
                "depth": len(req.prompt) + len(req.out_tokens),
                "remaining": st.remaining,
                "age_s": (round(now - req.t_submit, 6)
                          if req.t_submit is not None else None),
            }
            if self._paged:
                # Block-table depth: shared prefix blocks + private
                # chain — the per-slot footprint the dense engine's
                # fixed [L] rows could never show.
                entry["blocks"] = st.first_block + len(st.blocks)
                entry["shared_blocks"] = st.first_block
            if st.dfa is not None:
                # Automaton column: where this constrained stream's
                # host-side state machine sits right now — a stream
                # wedged mid-grammar shows as a stuck state here.
                entry["automaton_state"] = st.dfa_state
            if self._spec and st.spec_drafted:
                # Accept-rate column: this request's committed drafts
                # over its proposed drafts — the per-slot view of how
                # well the draft model is predicting THIS stream.
                entry["accept_rate"] = round(
                    st.spec_accepted / st.spec_drafted, 3)
            if st.prefill is not None:
                entry["prefill"] = {
                    "pos": st.prefill.pos,
                    "prompt_tokens": len(req.prompt),
                    "chunks_done": st.prefill.chunks_done,
                }
            slots.append(entry)
        out = {
            "slots": slots,
            "active_slots": self.active_slots,
            "queue": self.scheduler.debugz(now),
            "tenants": self.tenant_snapshot(),
            "stopping": self._stopping,
            "pending_swap": self._pending_swap is not None,
            "decode_compile_count": self.decode_compile_count(),
            "weight_version": self.weight_version,
            "request_kinds": self.metrics.kind_counters(),
            "pipeline": {
                "depth": self.pipeline_depth,
                "inflight": (self._inflight[-1].kind
                             if self._inflight else None),
                "inflight_ticks": len(self._inflight),
                "ticks_logged": len(self._tick_log),
                "host_gap_p50_s": self.metrics.host_gap.gap_p50,
                "device_idle_ratio": self.metrics.host_gap.idle_ratio,
            },
        }
        if self._pp > 1:
            out["pipeline"]["stages"] = self._pp
            out["pipeline"]["micro_batches"] = self._mb_count
            out["pipeline"]["bubble_fraction"] = (
                self.metrics.bubble.fraction)
        if self.mesh is not None:
            out["mesh"] = self.mesh_info()
        if self._spec:
            drafted = self.metrics.spec_draft_tokens
            out["speculative"] = {
                "spec_k": self.spec_k,
                "draft_model": getattr(self.draft_model, "name",
                                       str(self.draft_model)),
                "draft_tokens": drafted,
                "accepted_tokens": self.metrics.spec_accepted_tokens,
                "accept_rate": (round(
                    self.metrics.spec_accepted_tokens / drafted, 4)
                    if drafted else None),
            }
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.debugz()
        if self.kv_pool is not None:
            out["kv_pool"] = {
                **self.kv_pool.debugz(),
                "blocks_free": self.kv_pool.blocks_free,
                "preemptions": self.metrics.preemptions,
                "oom_rejections": self.metrics.oom_rejections,
                "kv_migrations": self.metrics.kv_migrations,
                "kv_migration_fallbacks":
                    self.metrics.kv_migration_fallbacks,
                "kv_migration_bytes": self.metrics.kv_migration_bytes,
                "kv_exports": self.metrics.kv_exports,
            }
            if self.kv_tier is not None:
                # Tier section on the kv_pool page: occupancy of the
                # host/disk levels plus the engine's traffic through
                # them (device resident bytes ride along so all three
                # tiers of the hierarchy read off one dict).
                self.metrics.set_kv_tier_resident_bytes(
                    self.kv_pool.blocks_used
                    * (self.kv_pool.bytes_per_block or 0))
                out["kv_tier"] = {
                    **self.kv_tier.stats(),
                    "resident_bytes": self.kv_pool.blocks_used
                    * (self.kv_pool.bytes_per_block or 0),
                    "spills": self.metrics.kv_spills,
                    "spill_bytes": self.metrics.kv_spill_bytes,
                    "readmits": self.metrics.kv_readmits,
                    "readmit_bytes": self.metrics.kv_readmit_bytes,
                    "pushes": self.metrics.kv_pushes,
                    "push_bytes": self.metrics.kv_push_bytes,
                    "push_fallbacks": self.metrics.kv_push_fallbacks,
                }
        if self.flight_recorder is not None:
            out["flight_recorder"] = self.flight_recorder.stats()
        if self.trace_store is not None:
            out["trace_store"] = self.trace_store.stats()
        if self.slo_s is not None:
            out["slo_s"] = self.slo_s
        return out

    # -- submission ---------------------------------------------------------
    def _build_request(
        self,
        prompt,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        priority: int = 0,
        timeout: float | None = None,
        trace_id: str | None = None,
        speculate: bool = True,
        tenant: str = "default",
        resume_tokens=None,
        kind: str = "generate",
        n: int = 1,
        constraint=None,
    ) -> Request:
        """Validation half of submission: everything that can reject a
        request typed BEFORE it touches the scheduler — shared by
        :meth:`submit` and the batched :meth:`submit_many`. Contradictory
        kind combinations (score with max_new_tokens, n>1 outside
        sample, a constraint on an unconstrained engine) reject typed
        HERE — a bad request must fail at admission, never mid-stream.

        ``resume_tokens``: output tokens the client ALREADY received on
        another replica (live slot migration off a draining peer): they
        pre-seed ``out_tokens``, so admission prefills prompt + resume
        and the first sampled token CONTINUES the stream instead of
        restarting it — the same fold-streamed-tokens-into-prefill
        contract paged preemption uses in-process, applied over the
        wire. They count against ``max_new_tokens`` and are never
        re-streamed."""
        if self._stopping:
            raise EngineStopped("engine is shutting down; not admitting")
        kind = str(kind or "generate")
        n = int(n or 1)
        if kind not in REQUEST_KINDS:
            raise ValueError(
                f"unknown request kind {kind!r}; expected one of "
                f"{REQUEST_KINDS}")
        if kind != "generate" and (not self._paged or self._pp > 1):
            raise ValueError(
                f"kind={kind!r} requires a paged single-stage engine "
                f"(kv_pool_mb / kv_pool_blocks, pp=1)")
        if kind in SCORELIKE_KINDS:
            if max_new_tokens > 0:
                raise ValueError(
                    f"kind={kind!r} is prefill-only: max_new_tokens must "
                    f"be 0, got {max_new_tokens}")
            speculate = False
        if kind == "sample":
            if n < 2:
                raise ValueError(
                    f"kind='sample' requires n >= 2 forks, got {n}")
            if n > self.slots:
                raise ValueError(
                    f"n={n} forks exceed the engine's {self.slots} slots")
            if self._spec and speculate:
                raise ValueError(
                    "n>1 forked sampling does not compose with "
                    "speculative decoding; pass speculate=False")
            speculate = False
        elif n != 1:
            raise ValueError(f"n={n} requires kind='sample'")
        dfa = None
        if constraint is not None:
            if not self._constrained_mode:
                raise ValueError(
                    "this engine was not built with constrained=True; "
                    "token-mask constraints are unavailable")
            if kind != "generate":
                raise ValueError(
                    f"constraint requires kind='generate', got {kind!r}")
            dfa = (constraint if isinstance(constraint, TokenDFA)
                   else TokenDFA.from_spec(constraint))
            if dfa.max_token() >= self._cfg.vocab_size:
                raise ValueError(
                    f"constraint references token {dfa.max_token()} "
                    f">= vocab_size {self._cfg.vocab_size}")
        prompt_arr = np.asarray(prompt, np.int32)
        if prompt_arr.ndim == 2 and prompt_arr.shape[0] == 1:
            prompt_arr = prompt_arr[0]
        if prompt_arr.ndim != 1 or prompt_arr.size < 1:
            raise ValueError(f"prompt must be a non-empty 1-D token list; "
                             f"got shape {prompt_arr.shape}")
        if kind in SCORELIKE_KINDS:
            # Prefill-only: the whole prompt must fit the context; no
            # decode budget to bound.
            if prompt_arr.size > self.limit:
                raise ValueError(
                    f"prompt ({prompt_arr.size}) exceeds this engine's "
                    f"context cap {self.limit}")
        else:
            _check_context(self.model, self._cfg, prompt_arr[None, :],
                           max_new_tokens)
        if kind not in SCORELIKE_KINDS \
                and prompt_arr.size + max_new_tokens > self.limit:
            # Tighter than the model's trained context: the engine's
            # max_context cap (dense mode: the pre-reserved per-slot
            # cache length under the byte budget).
            raise ValueError(
                f"prompt ({prompt_arr.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds this engine's context cap "
                f"{self.limit} (max_context)")
        if self._paged:
            # Resident K/V at completion: every position except the last
            # sampled token's (never fed back). A request that can never
            # fit the pool is a sizing error — reject typed, up front.
            bt = self.kv_block_tokens
            if kind in SCORELIKE_KINDS:
                # Scorelike feeds every prompt token, so all of them
                # are resident at completion.
                need = -(-prompt_arr.size // bt)
            elif kind == "sample":
                # n forks share the prompt's COMPLETE blocks; each owns
                # the rest (partial tail copy + decode growth) itself.
                resident = prompt_arr.size + max_new_tokens - 1
                shared = prompt_arr.size // bt
                need = shared + n * (-(-resident // bt) - shared)
            else:
                resident = prompt_arr.size + max_new_tokens - 1
                need = -(-resident // bt)
            if need > self.kv_pool.capacity:
                self.metrics.record_oom_reject()
                raise PoolExhausted(
                    f"request needs {need} KV blocks at completion; the "
                    f"pool holds {self.kv_pool.capacity} — raise "
                    f"--kv-pool-mb or lower max_new_tokens")
        req = Request(
            prompt_arr.tolist(), max_new_tokens, temperature=temperature,
            priority=priority, timeout=timeout, trace_id=trace_id,
            speculate=speculate, tenant=tenant,
            kind=kind, n=n, constraint=dfa,
        )
        if resume_tokens:
            try:
                resume = [int(t) for t in resume_tokens]
            except (TypeError, ValueError) as e:
                raise ValueError(f"bad resume_tokens: {e}") from None
            if len(resume) >= max_new_tokens:
                raise ValueError(
                    f"resume_tokens ({len(resume)}) >= max_new_tokens "
                    f"({max_new_tokens}): nothing left to decode")
            # Pre-seed the streamed prefix: _resident_tokens, the resume
            # prefill, quota cost, and the slot's remaining budget all
            # read prompt + out_tokens — the resumed request is
            # indistinguishable from a locally preempted one.
            req.out_tokens = resume
        if self._trace_requests:
            req.trace = TimelineRecord(req.trace_id, "engine",
                                       self.trace_source)
            req.trace.data["kind"] = req.kind
            req.trace.event("submit", prompt_tokens=len(req.prompt),
                            max_new_tokens=req.max_new_tokens,
                            priority=req.priority, tenant=req.tenant,
                            kind=req.kind)
        return req

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        priority: int = 0,
        timeout: float | None = None,
        trace_id: str | None = None,
        speculate: bool = True,
        tenant: str = "default",
        resume_tokens=None,
        kind: str = "generate",
        n: int = 1,
        constraint=None,
    ) -> Request:
        """Validate and enqueue a request; returns the streaming handle.

        Raises :class:`ValueError` (bad prompt / context overflow),
        :class:`QueueFullError` (backpressure),
        :class:`TenantOverQuota` (the tenant's token-rate budget has no
        room), or :class:`EngineStopped` (shutting down) — all before
        any device work.
        """
        req = self._build_request(
            prompt, max_new_tokens, temperature=temperature,
            priority=priority, timeout=timeout, trace_id=trace_id,
            speculate=speculate, tenant=tenant,
            resume_tokens=resume_tokens, kind=kind, n=n,
            constraint=constraint)
        try:
            self.scheduler.submit(req)
        except ServingError:
            self.metrics.record_reject()
            raise
        self.metrics.record_request_kind(req.kind)
        return req

    def submit_many(self, specs) -> list:
        """Batched admission for the binary front door: every spec that
        arrived in one event-loop tick is validated and handed to the
        scheduler in ONE ``submit_many`` call (one clock read, one
        arrival wake-up). Returns a list aligned with ``specs``: a
        :class:`Request` per accepted entry, the typed exception
        (:class:`ServingError` or ``ValueError``-shaped bad input) per
        rejected one — different streams on one connection fail
        independently."""
        built: list = []
        for spec in specs:
            try:
                built.append(self._build_request(
                    spec["prompt"], spec["max_new_tokens"],
                    temperature=float(spec.get("temperature", 0.0)),
                    priority=int(spec.get("priority", 0)),
                    timeout=spec.get("timeout"),
                    trace_id=spec.get("trace_id"),
                    speculate=bool(spec.get("speculate", True)),
                    tenant=str(spec.get("tenant") or "default"),
                    resume_tokens=spec.get("resume_tokens"),
                    kind=str(spec.get("kind") or "generate"),
                    n=int(spec.get("n") or 1),
                    constraint=spec.get("constraint"),
                ))
            except (ServingError, KeyError, TypeError, ValueError) as e:
                built.append(e)
        reqs = [r for r in built if isinstance(r, Request)]
        outcomes = iter(self.scheduler.submit_many(reqs))
        out: list = []
        for r in built:
            if not isinstance(r, Request):
                self.metrics.record_reject()
                out.append(r)
                continue
            err = next(outcomes)
            if err is not None:
                self.metrics.record_reject()
                out.append(err)
            else:
                self.metrics.record_request_kind(r.kind)
                out.append(r)
        return out

    # -- lifecycle ----------------------------------------------------------
    def shutdown(self, drain: bool = True) -> None:
        """Stop admitting. ``drain=True`` finishes in-flight requests
        before :meth:`run` returns; ``drain=False`` errors them out."""
        self._stopping = True
        self._draining = drain
        if self.flight_recorder is not None:
            self.flight_recorder.record_event("shutdown", drain=drain)
        self.scheduler.kick()

    def request_param_swap(self, variables, provenance: dict | None = None):
        """Queue an in-place parameter swap (the replica half of the
        cluster's zero-downtime weight reload).

        ``provenance`` is the new weights' version stamp
        (``checkpoint.weights_provenance`` of the file being reloaded);
        it becomes the engine's :attr:`weight_version` when the swap
        lands, so every post-swap response names the new checkpoint.
        Without one (inline callers), the version is bumped by one with
        no digest — still distinguishable per swap.

        ``variables`` is either a full variables dict (``{"params": ...}``,
        the ``save_weights`` / ``checkpoint.save_weights_file`` layout) or
        a bare params pytree. Leaf shapes and dtypes must match the
        serving model exactly — a mismatched tree raises ``ValueError``
        HERE rather than retracing (or silently corrupting) the compiled
        decode step later.

        The swap itself runs inside the engine loop at the first
        iteration with **no slot in flight** (the loop serializes all
        device work, so there is no race against a decode or prefill in
        the executor): params are device_put, the prefix cache is flushed
        (its pooled K/V was computed under the OLD weights), and one
        decode tick rewarms the step — under an armed auditor that tick
        PROVES the swap did not retrace. Returns ``(event, result)``:
        await the event, then check ``result`` for ``"error"``. Under
        continuous direct load the engine may never go idle — the cluster
        router drains the replica first, which is what guarantees the
        swap runs; a standalone server relies on a quiet moment.
        """
        if self._pending_swap is not None:
            # Overwriting would strand the first caller's event forever
            # (a silent false "busy" after its full timeout) and drop one
            # weights file without a trace.
            raise RuntimeError("a parameter swap is already pending")
        tree = variables
        if isinstance(tree, dict) and "params" in tree:
            tree = tree["params"]
        new_leaves, _ = jax.tree.flatten(tree)
        if self._pp > 1:
            # The live per-stage list duplicates the tied embedding
            # (stage 0 + last); validate against the UNSPLIT abstract
            # template the ctor captured, and hand _swap_sync the whole
            # tree — it re-splits along the stage plan.
            cur_leaves, cur_def = self._swap_template
        else:
            cur_leaves, cur_def = jax.tree.flatten(self._params)
        if len(new_leaves) != len(cur_leaves):
            raise ValueError(
                f"reload weights have {len(new_leaves)} leaves; serving "
                f"model has {len(cur_leaves)}")
        for i, (a, b) in enumerate(zip(new_leaves, cur_leaves)):
            a = np.asarray(a) if np.isscalar(a) else a
            if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
                raise ValueError(
                    f"reload weight leaf {i} is {a.dtype}{tuple(a.shape)}; "
                    f"serving model expects {b.dtype}{tuple(b.shape)}")
        # Re-hang the new leaves on the CURRENT treedef: dict vs FrozenDict
        # (or attr-ordering) differences between a weights file and the
        # live tree must not matter as long as the leaves line up.
        params = jax.tree.unflatten(cur_def, new_leaves)
        if provenance is None:
            provenance = {
                "version": int(self.weight_version.get("version") or 0) + 1,
                "digest": None,
            }
        else:
            provenance = _public_provenance(provenance)
        event: asyncio.Event = asyncio.Event()
        result: dict = {}
        self._pending_swap = (params, event, result, provenance)
        self.scheduler.kick()  # wake an idle run loop now
        return event, result

    def cancel_param_swap(self, event: asyncio.Event) -> bool:
        """Withdraw a pending swap (reload-verb timeout path). True if it
        was still pending; False if the loop already consumed it."""
        if self._pending_swap is not None and self._pending_swap[1] is event:
            self._pending_swap = None
            return True
        return False

    # -- KV block migration (serving/kv_transfer.py) -------------------------
    def request_kv_export(self, prompt):
        """Queue a KV block export: serialize the pool's longest
        complete-block chain for ``prompt`` (prefix trie hit — a slot
        that finished or preempted has ADOPTED its blocks there, so
        "export a slot's blocks" and "export a cached prefix" are one
        walk). Serviced by the run loop between iterations; returns
        ``(event, result)`` — await the event, then read ``result``
        (``payload`` bytes + ``matched_tokens``, or ``error``). Raises
        :class:`~distkeras_tpu.serving.kv_transfer.KVTransferError`
        immediately on a dense engine (blocks only exist paged)."""
        from distkeras_tpu.serving.kv_transfer import KVTransferError

        if not self._paged:
            raise KVTransferError(
                "KV export requires a paged engine (--paged / "
                "--kv-pool-mb): dense caches have no block bookkeeping")
        if self._pp > 1:
            raise KVTransferError(
                "KV export is not supported on a pp mesh yet: the "
                "gather program spans the whole pool, which is "
                "stage-partitioned under pp")
        event: asyncio.Event = asyncio.Event()
        result: dict = {}
        self._pending_kv.append(("export", prompt, event, result))
        self.scheduler.kick()
        return event, result

    def request_kv_import(self, payload: bytes):
        """Queue a KV block import: validate a peer's KVX1 payload
        (geometry + weight provenance), adopt its block chain into the
        pool's trie, and upload the rows — after which an admission for
        the same prompt is a zero-copy prefix hit. Same ``(event,
        result)`` contract as :meth:`request_kv_export`; a pool-dry
        receiver adopts what fits (possibly nothing) and reports it in
        ``result`` rather than failing — import must only ever help."""
        from distkeras_tpu.serving.kv_transfer import KVTransferError

        if not self._paged:
            raise KVTransferError(
                "KV import requires a paged engine (--paged / "
                "--kv-pool-mb)")
        if self._pp > 1:
            raise KVTransferError(
                "KV import is not supported on a pp mesh yet: the "
                "scatter program spans the whole pool, which is "
                "stage-partitioned under pp")
        event: asyncio.Event = asyncio.Event()
        result: dict = {}
        self._pending_kv.append(("import", payload, event, result))
        self.scheduler.kick()
        return event, result

    def _kv_export_sync(self, prompt) -> dict:
        """Executor-thread export: pin the chain, gather its pool rows,
        serialize. The pin only needs to span this call — the engine
        loop serializes every pool mutation."""
        from distkeras_tpu.serving.kv_transfer import (
            MAX_TOTAL_TRANSFER_BYTES,
            KVTransferError,
            serialize_blocks,
        )

        tokens = [int(t) for t in prompt]
        bt = self.kv_block_tokens
        match = self.kv_pool.match_blocks(tokens)
        try:
            n = len(match.ids)
            leaves = []
            if n:
                padded = self._pad_kv_ids(match.ids, fill=0)
                rows = self._kv_gather(self._cache, jnp.asarray(padded))
                leaves = [np.asarray(l)[:n] for l in jax.tree.leaves(rows)
                          if l.ndim > 1]
            # Tier-owner exports: continue the chain from the host/disk
            # tier where the device trie ends — an evicted-but-spilled
            # family stays exportable to the fleet (the directory's
            # owner contract), at zero device cost per tier block.
            n = self._extend_export_from_tier(tokens, n, leaves)
            if n == 0:
                return {"matched_tokens": 0, "blocks": 0, "payload": None}
            payload = serialize_blocks(
                tokens[:n * bt], leaves, block_tokens=bt,
                provenance=self.weight_version)
        finally:
            self.kv_pool.release(match)
        if len(payload) > MAX_TOTAL_TRANSFER_BYTES:
            # Oversize chains split across sequenced KVBLK frames on
            # the wire (kv_transfer.split_frames); only a chain past
            # the TOTAL cap is refused typed.
            raise KVTransferError(
                f"serialized blocks ({len(payload)} bytes) exceed the "
                f"transfer cap ({MAX_TOTAL_TRANSFER_BYTES}); receiver "
                f"falls back to monolithic prefill")
        self.metrics.record_kv_export(len(payload))
        return {"matched_tokens": n * self.kv_block_tokens, "blocks": n,
                "bytes": len(payload), "payload": payload}

    def _kv_import_sync(self, payload) -> dict:
        """Executor-thread import: validate geometry + provenance
        (typed rejects), adopt the chain, scatter the new rows."""
        from distkeras_tpu.serving.kv_transfer import (
            KVTransferError,
            deserialize_blocks,
        )

        header, leaves = deserialize_blocks(payload)
        if int(header["block_tokens"]) != self.kv_block_tokens:
            raise KVTransferError(
                f"block geometry mismatch: peer blocks hold "
                f"{header['block_tokens']} tokens, this pool "
                f"{self.kv_block_tokens}")
        mine = [l for l in jax.tree.leaves(self._cache) if l.ndim > 1]
        theirs = header.get("leaves", [])
        if len(theirs) != len(mine):
            raise KVTransferError(
                f"KV leaf count mismatch: payload has {len(theirs)}, "
                f"this pool {len(mine)}")
        for i, (meta, leaf) in enumerate(zip(theirs, mine)):
            want = (tuple(int(s) for s in meta["shape"][1:]),
                    str(meta["dtype"]))
            have = (tuple(leaf.shape[1:]), np.dtype(leaf.dtype).name)
            if want != have:
                raise KVTransferError(
                    f"KV leaf {i} geometry mismatch: payload "
                    f"{want[1]}{want[0]}, this pool {have[1]}{have[0]}")
        prov = header.get("provenance") or {}
        mine_prov = self.weight_version
        if (int(prov.get("version") or 0), prov.get("digest")) != (
                int(mine_prov.get("version") or 0),
                mine_prov.get("digest")):
            # KV is a pure function of (weights, tokens): adopting
            # blocks computed under other weights would poison every
            # later hit. Typed reject; the caller prefills monolithic.
            raise KVTransferError(
                f"weight provenance mismatch: blocks computed under "
                f"v{prov.get('version')}/{prov.get('digest')}, serving "
                f"v{mine_prov.get('version')}/{mine_prov.get('digest')}")
        tokens = [int(t) for t in header.get("tokens", [])]
        n_blocks = int(header.get("n_blocks") or 0)
        uploads, resident = self.kv_pool.adopt_foreign(tokens, n_blocks)
        if uploads:
            idxs = [i for i, _ in uploads]
            rows = np.asarray([r for _, r in uploads], np.int32)
            padded = self._pad_kv_ids(rows, fill=self.kv_pool.capacity)
            b = len(padded)
            treedef = jax.tree.structure(self._cache)
            data_leaves = []
            src = iter(leaves)
            for leaf in jax.tree.leaves(self._cache):
                if leaf.ndim <= 1:
                    data_leaves.append(jnp.zeros((b, 0), leaf.dtype))
                    continue
                arr = next(src)[idxs]
                if len(idxs) < b:  # pad to the pow2 bucket (dropped)
                    pad = np.zeros((b - len(idxs),) + arr.shape[1:],
                                   arr.dtype)
                    arr = np.concatenate([arr, pad], axis=0)
                data_leaves.append(jnp.asarray(arr))
            data = jax.tree.unflatten(treedef, data_leaves)
            self._cache = self._kv_scatter(self._cache, data,
                                           jnp.asarray(padded))
        return {"adopted_blocks": len(uploads),
                "resident_blocks": resident,
                "matched_tokens": resident * self.kv_block_tokens,
                "bytes": len(payload)}

    def _pad_kv_ids(self, ids, fill: int) -> np.ndarray:
        """Pow2-pad a pool row-id vector so the KV gather/scatter
        programs compile once per bucket (the pool's _pad_ids rule,
        applied to transfer ops)."""
        n = len(ids)
        b = 1
        while b < n:
            b *= 2
        out = np.full((b,), fill, np.int32)
        out[:n] = ids
        return out

    # -- tiered KV cache (serving/kv_tier.py) -------------------------------
    def _spill_block(self, chain_tokens, row: int) -> None:
        """Pool spill hook: serialize ONE eviction victim's pool row
        into the host tier as exact KVX1 bytes, keyed by its full
        root→block token chain. Runs inside ``_BlockTrie._alloc`` —
        always on the engine loop, or on the executor while the loop
        awaits it, and always after a pipeline barrier, so the gather
        cannot race a donated in-flight tick. The payload is the same
        serialization a peer transfer ships, so a spilled block is
        re-admittable locally AND exportable to the fleet."""
        tier = self.kv_tier
        if tier is None:
            return
        from distkeras_tpu.serving.kv_transfer import serialize_blocks

        t0 = time.monotonic()
        bt = self.kv_block_tokens
        padded = self._pad_kv_ids(np.asarray([row], np.int32), fill=0)
        rows = self._kv_gather(self._cache, jnp.asarray(padded))
        leaves = [np.asarray(l)[:1] for l in jax.tree.leaves(rows)
                  if l.ndim > 1]
        chain = [int(t) for t in chain_tokens]
        payload = serialize_blocks(chain[-bt:], leaves, block_tokens=bt,
                                   provenance=self.weight_version)
        if tier.put(chain, payload):
            self.metrics.record_kv_spill(
                len(payload), time.monotonic() - t0,
                trace_id=self._tier_trace_id)
            self.scheduler.note_kv_arrival()

    def _spill_blocks(self, victims) -> None:
        """Batched pool spill hook (``spill_many_hook``): serialize a
        whole allocation burst's eviction victims from ONE D2H gather —
        ``victims`` is the burst's ``(chain_tokens, row)`` list, rows
        still holding their KV bytes. The per-victim path gathers one
        pow2-padded row per eviction; a B-victim burst paid B gathers
        (each a full device round trip) where one batched gather over
        the padded row vector does — the exact shape of
        :meth:`_readmit_from_tier`'s one-scatter H2D side. Per-block
        spill latency is recorded as the burst's share, so the
        ``kv_tier_spill_seconds`` family directly shows the win."""
        tier = self.kv_tier
        if tier is None or not victims:
            return
        if len(victims) == 1:
            self._spill_block(*victims[0])
            return
        from distkeras_tpu.serving.kv_transfer import serialize_blocks

        t0 = time.monotonic()
        bt = self.kv_block_tokens
        n = len(victims)
        rows = np.asarray([int(r) for _, r in victims], np.int32)
        padded = self._pad_kv_ids(rows, fill=0)
        gathered = self._kv_gather(self._cache, jnp.asarray(padded))
        leaves = [np.asarray(l)[:n]
                  for l in jax.tree.leaves(gathered) if l.ndim > 1]
        stored: list[int] = []
        for i, (chain_tokens, _row) in enumerate(victims):
            chain = [int(t) for t in chain_tokens]
            payload = serialize_blocks(
                chain[-bt:], [l[i:i + 1] for l in leaves],
                block_tokens=bt, provenance=self.weight_version)
            if tier.put(chain, payload):
                stored.append(len(payload))
        per_block_s = (time.monotonic() - t0) / n
        for nbytes in stored:
            self.metrics.record_kv_spill(nbytes, per_block_s,
                                         trace_id=self._tier_trace_id)
        if stored:
            self.scheduler.note_kv_arrival()

    def _tier_provenance_ok(self, header) -> bool:
        prov = header.get("provenance") or {}
        mine = self.weight_version
        return (int(prov.get("version") or 0), prov.get("digest")) == (
            int(mine.get("version") or 0), mine.get("digest"))

    def _readmit_from_tier(self, tokens, trace_id: str | None = None) -> int:
        """Extend the device trie along ``tokens`` from the host tier:
        for each complete block past the device-resident prefix, fetch
        its KVX1 payload, adopt a pool row (never preempting — adoption
        only reclaims unreferenced leaves), and H2D-scatter the bytes
        in ONE batched call. Runs on the loop thread during admission,
        after the pipeline barrier, BEFORE the trie match — so the
        re-admitted blocks count as the prefix hits they are. Returns
        the number of blocks re-admitted."""
        tier, pool = self.kv_tier, self.kv_pool
        if tier is None:
            return 0
        bt = self.kv_block_tokens
        toks = [int(t) for t in tokens]
        # Same last-block holdback as match(): prefill needs >= 1
        # uncached token, so a block match() won't use is a wasted row.
        cap = max(0, (len(toks) - 1) // bt)
        resident = pool.probe(toks) // bt
        if resident >= cap or not tier.contains(toks[:(resident + 1) * bt]):
            return 0
        from distkeras_tpu.serving.kv_transfer import deserialize_blocks

        t0 = time.monotonic()
        mine = [l for l in jax.tree.leaves(self._cache) if l.ndim > 1]
        staged: list[tuple[int, list]] = []  # (pool_row, per-leaf [1,bt,..])
        nbytes = 0
        k = resident
        while k < cap:
            chain = toks[:(k + 1) * bt]
            payload = tier.get(chain)
            if payload is None:
                break
            try:
                header, leaves = deserialize_blocks(payload)
            except Exception:
                break  # truncated/corrupt entry: stop, never raise
            if (int(header.get("block_tokens") or 0) != bt
                    or len(leaves) != len(mine)
                    or not self._tier_provenance_ok(header)):
                break
            # adopt_foreign re-walks the chain from the root: resident
            # prefix blocks are touched, block k gets a fresh row (or
            # none when the pool is dry — stop there, what fit is
            # already a win).
            uploads, res = pool.adopt_foreign(chain, k + 1)
            if not uploads:
                break
            staged.append((uploads[0][1], leaves))
            nbytes += len(payload)
            k += 1
        if not staged:
            return 0
        rows = np.asarray([r for r, _ in staged], np.int32)
        padded = self._pad_kv_ids(rows, fill=self.kv_pool.capacity)
        b = len(padded)
        treedef = jax.tree.structure(self._cache)
        data_leaves, li = [], 0
        for leaf in jax.tree.leaves(self._cache):
            if leaf.ndim <= 1:
                data_leaves.append(jnp.zeros((b, 0), leaf.dtype))
                continue
            arr = np.concatenate([blk[li] for _, blk in staged], axis=0)
            if len(staged) < b:  # pad to the pow2 bucket (dropped)
                pad = np.zeros((b - len(staged),) + arr.shape[1:],
                               arr.dtype)
                arr = np.concatenate([arr, pad], axis=0)
            data_leaves.append(jnp.asarray(arr))
            li += 1
        data = jax.tree.unflatten(treedef, data_leaves)
        self._cache = self._kv_scatter(self._cache, data,
                                       jnp.asarray(padded))
        self.metrics.record_kv_readmit(len(staged), nbytes,
                                       time.monotonic() - t0,
                                       trace_id=trace_id)
        self.scheduler.note_kv_arrival()
        return len(staged)

    def _extend_export_from_tier(self, tokens, n: int, leaves: list) -> int:
        """Continue an export chain past the device-resident prefix
        using host-tier payloads: deserialize each contiguous tier
        block and append its leaf rows to ``leaves`` (in place).
        Returns the new block count. Export has NO last-block holdback
        (mirrors ``match_blocks``): a peer adopting the chain wants the
        full spilled prefix."""
        tier = self.kv_tier
        if tier is None:
            return n
        from distkeras_tpu.serving.kv_transfer import deserialize_blocks

        bt = self.kv_block_tokens
        n_total = len(tokens) // bt
        extras, k = [], n
        while k < n_total:
            payload = tier.get(tokens[:(k + 1) * bt])
            if payload is None:
                break
            try:
                header, lv = deserialize_blocks(payload)
            except Exception:
                break
            if (int(header.get("block_tokens") or 0) != bt
                    or not self._tier_provenance_ok(header)):
                break
            want = len(leaves) if leaves else (
                len(extras[0]) if extras else len(lv))
            if len(lv) != want or not lv:
                break
            extras.append(lv)
            k += 1
        if not extras:
            return n
        if not leaves:
            leaves.extend(
                np.concatenate([e[i] for e in extras], axis=0)
                for i in range(len(extras[0])))
        else:
            for i in range(len(leaves)):
                leaves[i] = np.concatenate(
                    [leaves[i]] + [e[i] for e in extras], axis=0)
        return k

    def _tier_pending(self, req) -> bool:
        """True when a parked request's next uncovered block sits in
        the host tier (or a peer import is queued) — i.e. waiting on a
        tier arrival, not on a slot to free."""
        if self.kv_tier is None:
            return bool(self._pending_kv)
        if self._pending_kv:
            return True
        toks = [int(t) for t in req.prompt]
        bt = self.kv_block_tokens
        resident = self.kv_pool.probe(toks) // bt
        return self.kv_tier.contains(toks[:(resident + 1) * bt])

    async def wait_for_kv(self, tokens, timeout_s: float) -> bool:
        """Await KV residency for ``tokens``' first block in ANY local
        tier (device pool or host tier) — the decode-side wait behind a
        router-scheduled push (``kv_wait``): instead of pulling at
        admission, the server parks the request here until the pushed
        bytes land (the import path fires the scheduler's tier-arrival
        event). Returns True when resident, False on timeout (caller
        pulls or re-prefills — counted fallbacks, never errors)."""
        if not self._paged:
            return False
        toks = [int(t) for t in tokens]
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while True:
            if self.kv_pool.probe(toks) > 0 or (
                    self.kv_tier is not None
                    and self.kv_tier.probe(toks) > 0):
                return True
            remaining = deadline - loop.time()
            if remaining <= 0:
                return False
            await self.scheduler.wait_for_kv_arrival(remaining)

    def _swap_sync(self, params) -> None:
        """Executor-thread half of the swap: transfer, flush, rewarm.

        Sharded engines place the candidate SHARD-THEN-PLACE: each host
        leaf is sliced straight into its mesh layout, so a rolling
        weight update to a tp-sharded replica transfers bytes/tp per
        device and never materializes a replicated copy per device —
        the arXiv:2004.13336 move applied to weight rollout."""
        from distkeras_tpu.parallel.gspmd import place_sharded

        if self._pp > 1:
            # Per-stage shard-then-place: the full host tree is split
            # along the stage plan and each stage's subtree is sliced
            # straight into ITS devices' layouts — a rolling update to
            # a tp×pp replica transfers bytes/(tp·pp) per device.
            params = [
                place_sharded(part, sh)
                for part, sh in zip(self._stage_plan.split_params(params),
                                    self._param_shardings)]
        else:
            params = place_sharded(params, self._param_shardings)
        jax.block_until_ready(params)
        self._params = params
        if self.prefix_cache is not None:
            # Pooled K/V is a pure function of (weights, tokens): stale
            # weights make every cached block wrong, so the whole pool is
            # invalidated in one stroke.
            self.prefix_cache.flush()
        if self.kv_pool is not None:
            # Safe for the same reason the swap itself is: zero active
            # slots means zero slot-owned blocks, so the flush only
            # drops (now-wrong) trie entries. flush() bypasses _alloc,
            # so no spill fires — old-weight blocks never reach the
            # host tier.
            self.kv_pool.flush()
        if self.kv_tier is not None:
            # The host/disk tiers hold serialized KV from the OLD
            # weights — same purity argument, one stroke.
            self.kv_tier.flush()
        # Rewarm: one decode tick over the (all-free) batch. Garbage
        # output, real proof — the compiled decode step runs against the
        # new params, so an armed auditor raises here if the swap somehow
        # changed an aval, and the first real request pays no first-touch
        # latency.
        self._decode_sync()

    def reopen(self) -> None:
        """Re-arm admission after a drain shutdown. The compiled programs
        and slot caches persist, so a bench can run several load phases on
        one engine without re-paying compilation."""
        if self._running:
            raise RuntimeError("cannot reopen while run() is active")
        self._stopping = False
        self._draining = True
        self.scheduler.reset_loop_state()

    async def run(self, idle_poll_s: float = 0.05) -> None:
        """Main loop: expire, admit, decode, stream — until shutdown."""
        if self._running:
            raise RuntimeError("engine.run() is already active")
        self._running = True
        if self.flight_recorder is not None:
            self.flight_recorder.record_event("engine_start",
                                              slots=self.slots)
        loop = asyncio.get_running_loop()
        try:
            while True:
                now = time.monotonic()
                # 1. Shed queued requests that died waiting: deadline
                # passed, or caller cancelled (client disconnect).
                for req in self.scheduler.expire(now):
                    if req.cancelled:
                        self._finish_error(req, RequestCancelled(
                            "cancelled while queued"))
                    else:
                        self.metrics.record_expire()
                        self._finish_error(req, RequestTimeout(
                            f"deadline exceeded after {req.timeout}s in queue"))
                # 2. Free active slots whose request died mid-decode.
                # Teardown changes batch content — a pipeline barrier
                # first, so no in-flight tick is reading the blocks the
                # teardown releases. (The barrier may FINISH some of the
                # candidates; re-check before tearing down.)
                dead = [i for i, st in enumerate(self._slot_state)
                        if st is not None
                        and (st.request.cancelled
                             or (st.request.deadline is not None
                                 and now > st.request.deadline))]
                if dead:
                    await self._pipeline_barrier(loop)
                for i in dead:
                    st = self._slot_state[i]
                    if st is None:
                        continue  # finished at the barrier
                    dl = st.request.deadline
                    if st.request.cancelled:
                        self._finish_error(st.request, RequestCancelled(
                            f"cancelled with {st.remaining} tokens undecoded"))
                        self._release_prefill(st)
                        self._free_slot_paged(i, st)
                        self._slot_state[i] = None
                    elif dl is not None and now > dl:
                        self.metrics.record_expire()
                        self._finish_error(st.request, RequestTimeout(
                            f"deadline exceeded after {st.request.timeout}s "
                            f"with {st.remaining} tokens undecoded"))
                        self._release_prefill(st)
                        self._free_slot_paged(i, st)
                        self._slot_state[i] = None
                # 3. Shutdown: flush the queue with typed errors.
                if self._stopping:
                    for req in self.scheduler.drain():
                        self._finish_error(
                            req, EngineStopped("engine shut down while queued"))
                # 3b. Pending parameter swap: runs only when NO slot is
                # in flight AND no queued request has streamed tokens (a
                # preempted-and-requeued resume must finish under the
                # weights that produced its streamed prefix — in-flight
                # requests finish under the weights they started with;
                # the cluster router guarantees this by draining the
                # replica first). Before admission, so a queued request
                # never splices old-weight prefix blocks.
                if (self._pending_swap is not None
                        and self.active_slots == 0
                        and not self.scheduler.has_streamed()):
                    # Zero ACTIVE slots can still mean one in-flight
                    # tick (the speculative tick dispatched before its
                    # rows' finishes were known): a swap waits for zero
                    # in-flight ticks, full stop.
                    await self._pipeline_barrier(loop)
                    params, ev, res, prov = self._pending_swap
                    self._pending_swap = None
                    if self.flight_recorder is not None:
                        self.flight_recorder.record_event(
                            "param_swap",
                            version=prov.get("version"),
                            digest=prov.get("digest"))
                    with span("param_swap"):
                        try:
                            await self._in_executor(
                                loop, self._swap_sync, params)
                            self.weight_version = prov
                            self.metrics.set_weight_version(prov)
                            res["ok"] = True
                            res["weight_version"] = prov
                        except Exception as e:
                            res["error"] = e
                        finally:
                            if not res:
                                # BaseException (task cancelled mid-
                                # swap): resolve the waiter before it
                                # propagates, or the reload verb hangs
                                # its full timeout.
                                res["error"] = ServingError(
                                    "engine died mid-swap")
                            ev.set()
                # 3c. KV block transfers (export to / import from a
                # peer replica): serviced between iterations, so the
                # gather/scatter can never race a decode step's donated
                # cache buffers. Device work in the executor, event
                # resolution on the loop thread.
                if self._paged and self._pending_kv:
                    # Barrier: the export gather / import scatter must
                    # never interleave with a tick that is mid-flight
                    # over the same pool rows.
                    await self._pipeline_barrier(loop)
                    ops, self._pending_kv = self._pending_kv, []
                    for kind, arg, ev, res in ops:
                        with span("kv_transfer", kind=kind):
                            try:
                                res.update(await self._in_executor(
                                    loop,
                                    (self._kv_export_sync
                                     if kind == "export"
                                     else self._kv_import_sync), arg))
                            except Exception as e:
                                res["error"] = e
                            finally:
                                ev.set()
                    # Imported blocks ARE a tier arrival: wake any
                    # tier-pending parked admission (and a decode-side
                    # wait_for_kv behind a router-scheduled push).
                    self.scheduler.note_kv_arrival()
                # 4. Admission: prefill queued requests into free slots.
                # Device work runs in the executor; stream/metrics
                # bookkeeping stays on the loop thread (asyncio queues and
                # events are not thread-safe).
                if (not self._stopping and len(self.scheduler)
                        and self.free_slots
                        and not (self._paged and self._parked_at_version
                                 == self.kv_pool.version
                                 and self.scheduler.peek()
                                 is self._parked_req)):
                    # Admission splices content into the batch (and,
                    # paged, reserves/preempts pool blocks): barrier
                    # first so the reserve can never race an in-flight
                    # tick's reads, and so the admit splice lands on
                    # harvested token state. The barrier may free MORE
                    # slots (a finishing tick), which only helps. A
                    # parked queue head (dry pool, nothing freed since)
                    # admits nobody — the admission loop below breaks
                    # on the same check — so it must NOT drain the
                    # pipeline every iteration: that would pay the full
                    # host gap per tick for the whole parked period.
                    await self._pipeline_barrier(loop)
                if not self._stopping:
                    while self.free_slots and len(self.scheduler):
                        if (self._paged and self._parked_at_version
                                == self.kv_pool.version
                                and self.scheduler.peek()
                                is self._parked_req):
                            # The queue head is parked on a dry pool and
                            # nothing has freed since — re-matching it
                            # every iteration would only burn host time.
                            # The head check keeps the park from gating
                            # ANYONE ELSE: a higher-priority arrival
                            # (which may preempt its way in) or the
                            # parked request expiring/cancelling changes
                            # the head and reopens admission without
                            # waiting for the pool version to move.
                            break
                        # Fresh clock per pop: an earlier admission's
                        # prefill may have taken long enough that more
                        # queued deadlines expired — a stale `now` would
                        # admit (and fully prefill) an already-dead
                        # request.
                        req = self.scheduler.pop(time.monotonic())
                        if req is None:
                            break
                        if (req.kind == "sample"
                                and self.free_slots < req.n):
                            # Fork fan-out needs all n slots claimed UP
                            # FRONT (a later admission must not steal a
                            # child's slot mid-prefill): requeue at the
                            # class head until n slots are free.
                            self.scheduler.requeue(req)
                            break
                        slot = self._slot_state.index(None)
                        paged_job = None
                        if self._paged:
                            paged_job = self._reserve_paged(req, slot)
                            if paged_job is None:
                                # Parked: requeued at its class head,
                                # admission resumes when blocks free.
                                break
                            self._parked_at_version = None
                            self._parked_req = None
                        # ADMISSION WAIT ends HERE (slot granted); the
                        # PREFILL DEVICE TIME is recorded separately when
                        # the prefill completes (record_prefill). The two
                        # series — plus chunk-interleave wait in chunked
                        # mode — make up TTFT, so an operator can tell
                        # queueing delay from prefill cost.
                        wait = time.monotonic() - req.t_submit
                        self.metrics.record_admit(wait)
                        # Wide-event columns (unconditional: the done-
                        # time record needs them with tracing off). The
                        # FIRST admission's wait is the queue wait; a
                        # re-admission after preemption keeps it.
                        if req.queue_wait_s is None:
                            req.queue_wait_s = wait
                            req.admit_iteration = self.metrics.iterations
                        # Provenance stamp, FIRST admission only: swaps
                        # run at zero active slots and never while a
                        # preempted resume is queued, so the first stamp
                        # IS completion-time provenance; a re-admission
                        # after preemption must keep the stamp its
                        # streamed prefix was served under.
                        if req.weight_version is None:
                            req.weight_version = self.weight_version
                        if req.trace is not None:
                            req.trace.data["weight_version"] = (
                                req.weight_version)
                            # Rendered as a slice ENDING here: the queue
                            # wait lane segment between submit and admit.
                            req.trace.event("admit", slot=slot,
                                            dur_s=round(wait, 9))
                            req.trace.data["queue_wait_s"] = round(wait, 9)
                            req.trace.data["admit_iteration"] = (
                                self.metrics.iterations)
                        if self.flight_recorder is not None:
                            self.flight_recorder.record_event(
                                "admit", trace_id=req.trace_id, slot=slot)
                        now_t = time.monotonic()
                        # Resume-aware: a preempted request re-admits
                        # with its already-streamed tokens folded into
                        # the prefill, so only the UNdecoded remainder
                        # is owed.
                        st = _SlotState(
                            req,
                            req.max_new_tokens - len(req.out_tokens),
                            now_t, t_admit=now_t)
                        if paged_job is not None:
                            (st.prefill, st.blocks, st.first_block,
                             st.match) = paged_job
                        if req.constraint is not None:
                            st.dfa = req.constraint
                            st.dfa_state = req.constraint.start
                        self._slot_state[slot] = st
                        if req.kind == "sample":
                            # Claim the n-1 child slots NOW (fork_wait:
                            # parked out of the decodable set until the
                            # parent prefill fans out).
                            st.fork_idx = 0
                            req.fork_completions = [None] * req.n
                            for _ in range(req.n - 1):
                                c = self._slot_state.index(None)
                                self._slot_state[c] = _SlotState(
                                    req, st.remaining, now_t,
                                    t_admit=now_t, fork_wait=True)
                        with span("admit", slot=slot,
                                  trace_id=req.trace_id,
                                  prompt_len=len(req.prompt),
                                  queue_wait_s=round(wait, 6)):
                            # Prefix-cache lookup + splice: a hit makes
                            # admission nearly free — the matched prefix's
                            # prefill compute is skipped entirely. (Paged
                            # admission already reserved its blocks and
                            # pinned its match — zero device work.)
                            if st.prefill is None:
                                st.prefill = await self._in_executor(
                                    loop, self._begin_prefill, req)
                            if self._chunk is None:
                                # Monolithic prefill: the whole uncached
                                # tail, admitted inline. Normally ONE
                                # call; near-context-limit prompts may
                                # split into a few pow2 sub-chunks (see
                                # _prefill_step's overshoot guard).
                                tok0 = None
                                while tok0 is None:
                                    tok0 = await self._in_executor(
                                        loop, self._prefill_step, st, slot)
                                self._route_admission(st, slot, tok0)
                # 4b. Chunked prefill: ONE chunk per iteration TOTAL,
                # round-robin across prefilling slots, interleaved with
                # the decode tick below — the decode batch never stalls
                # for more than a single chunk's device time no matter
                # how many prompts are admitting at once (concurrent
                # admissions stretch each other's TTFT instead). Runs
                # during drain shutdown too (a half-prefilled slot must
                # finish for run() to exit).
                if self._chunk is not None:
                    pending = [i for i, st in enumerate(self._slot_state)
                               if st is not None and st.prefill is not None]
                    if pending:
                        # A completing chunk admit-splices into the
                        # batch (and donates the token buffer): barrier
                        # before the chunk runs. Chunked admission
                        # phases therefore serialize with the decode
                        # tick exactly as before — the pipeline's win is
                        # the steady decode state between admissions.
                        await self._pipeline_barrier(loop)
                        start = self._prefill_rr
                        i = min(pending,
                                key=lambda s: (s - start) % self.slots)
                        self._prefill_rr = (i + 1) % self.slots
                        st = self._slot_state[i]
                        with span("prefill_tick", slot=i,
                                  offset=st.prefill.pos):
                            tok0 = await self._in_executor(
                                loop, self._prefill_step, st, i)
                        if tok0 is not None:
                            self._route_admission(st, i, tok0)
                # 5. Nothing active? Flush the pipeline (an in-flight
                # tick whose every row finished leaves active == 0 with
                # a garbage tick still pending) and wait.
                if self.active_slots == 0:
                    await self._pipeline_barrier(loop)
                    if self._stopping:
                        break
                    if (self._paged and self._parked_req is not None
                            and self._parked_at_version
                            == self.kv_pool.version
                            and self.scheduler.peek() is self._parked_req):
                        # Fully parked: the queue head is waiting on a
                        # dry pool and NOTHING is running that could
                        # free blocks — only an arrival, a cancel/kick,
                        # or a pool-version move (a KV import kicks) can
                        # change the picture. wait_for_request would
                        # return immediately on the non-empty queue and
                        # hot-spin the loop doing only the park check;
                        # wait on the arrival event itself instead (the
                        # timeout keeps deadline expiry responsive).
                        # Tier-pending heads (next uncovered block in
                        # the host tier, or a peer import queued) wait
                        # on the TIER-arrival event: the arrival wakes
                        # them immediately instead of them re-checking
                        # pool.version once per idle poll.
                        if self._tier_pending(self._parked_req):
                            await self.scheduler.wait_for_kv_arrival(
                                idle_poll_s)
                        else:
                            await self.scheduler.wait_for_wake(idle_poll_s)
                    else:
                        await self.scheduler.wait_for_request(idle_poll_s)
                    continue
                if self._stopping and not self._draining:
                    await self._pipeline_barrier(loop)
                    for i, st in enumerate(self._slot_state):
                        if st is not None:
                            self._finish_error(st.request, EngineStopped(
                                "engine shut down mid-decode"))
                            self._release_prefill(st)
                            self._free_slot_paged(i, st)
                            self._slot_state[i] = None
                    break
                # 5c. Paged growth: before the tick, every decoding slot
                # whose next write position crosses into an unallocated
                # block chains one more from the pool — preempting the
                # lowest-priority youngest slot (possibly itself) when
                # the pool is dry. Host bookkeeping only; the decode
                # step itself never changes shape. Growth mutates table
                # rows (and may preempt = tear down): barrier first, but
                # ONLY when some slot actually needs a block — the
                # common tick crosses no block boundary and keeps the
                # pipeline full.
                if self._paged:
                    if any(st is not None and st.prefill is None
                           and self._needs_tail_block(i)
                           for i, st in enumerate(self._slot_state)):
                        await self._pipeline_barrier(loop)
                    for i in range(self.slots):
                        st = self._slot_state[i]
                        if st is not None and st.prefill is None:
                            self._ensure_tail_block(i)
                # 6. One decode iteration for the whole batch — skipped
                # while EVERY active slot is still mid-prefill (the whole
                # tick's output would be discarded; the chunk in 4b was
                # this iteration's useful device work). With a draft
                # model, the tick is SPECULATIVE whenever any live row is
                # eligible (greedy + not opted out): draft K, verify
                # once, commit per-row accept prefixes — sampled rows in
                # the same batch commit their usual one token from the
                # verify's position-0 logits. All-sampled batches (and
                # the swap rewarm) take the one-token fallback step.
                await self._tick_step(loop)
                if self.inject_decode_delay_s > 0:
                    # Injected fault (SLO bench): stretch the host side
                    # of every iteration so observed latencies genuinely
                    # breach — never a synthetic metric write.
                    await asyncio.sleep(self.inject_decode_delay_s)
                self.metrics.sample(
                    len(self.scheduler), self.active_slots, self.slots)
                # Yield so the server can read sockets between iterations.
                await asyncio.sleep(0)
        except BaseException as e:
            # A device failure — or the embedder cancelling the run()
            # task directly (CancelledError is a BaseException) — must
            # not strand clients: every in-flight and queued request gets
            # a terminal error event before the exception propagates
            # (otherwise server handlers block forever on streams nothing
            # will ever finish).
            err = ServingError(f"engine failure: {e!r}")
            # Abandon any in-flight ticks: their device buffers are
            # dropped with the references; nothing host-side depends on
            # their results once every request below is errored out.
            self._inflight.clear()
            for i, st in enumerate(self._slot_state):
                if st is not None:
                    self._finish_error(st.request, err)
                    self._release_prefill(st)
                    # Crash path: free only (no adoption) — keep the
                    # last-words path as simple as possible.
                    self._free_slot_paged(i, st, adopt=False)
                    self._slot_state[i] = None
            for req in self.scheduler.drain():
                self._finish_error(req, err)
            # A pending param swap must resolve too, or the reload verb
            # blocks its full timeout and reports "busy" for an engine
            # that is in fact dead.
            if self._pending_swap is not None:
                _, ev, res, _ = self._pending_swap
                self._pending_swap = None
                res["error"] = err
                ev.set()
            # Same for pending KV transfers: a peer awaiting an export
            # must get its typed failure now, not a hung timeout.
            if self._paged and self._pending_kv:
                ops, self._pending_kv = self._pending_kv, []
                for _, _, ev, res in ops:
                    res["error"] = err
                    ev.set()
            self._stopping = True
            # Last words: the black box hits disk BEFORE the exception
            # propagates — a chaos-killed (task-cancelled) or device-
            # failed replica leaves its final state for the supervisor.
            if self.flight_recorder is not None:
                self.flight_recorder.crash_dump(error=repr(e))
            raise
        finally:
            self._running = False

    # -- decode pipeline ----------------------------------------------------
    async def _tick_step(self, loop) -> None:
        """One decode (or speculative) tick, pipelined. Plain → plain is
        the fully overlapped path: dispatch tick N+1 FIRST, then harvest
        and stream tick N while N+1 executes — the host bookkeeping for
        N (token pushes, teardown, metrics) plus the whole next loop
        iteration's steps 1–4 and the event-loop turn hide behind N+1's
        device time. A speculative tick (or ``pipeline_depth=0``)
        harvests before the next dispatch, because the next tick's
        position state depends on the commit counts only the harvest
        knows."""
        decodable = self._decodable()
        if not decodable:
            if self._inflight:
                # Every dispatched row disappeared (cancel barrier tore
                # them down before the harvest): flush so the stale
                # handles don't pin device buffers.
                await self._pipeline_barrier(loop)
            return

        def want_spec() -> bool:
            # A zero-accept row (every draft rejected last spec tick)
            # committed nothing; one interleaved fallback tick
            # guarantees it a token before speculation resumes —
            # re-speculating immediately would redraft the same
            # rejected proposal forever.
            return (self._spec
                    and not self._spec_owe_fallback
                    and any(
                        self._slot_state[i].request.temperature <= 0
                        and self._slot_state[i].request.speculate
                        for i in decodable))

        spec_tick = want_spec()
        constrained_live = (self._constrained_mode and any(
            self._slot_state[i].dfa is not None for i in decodable))
        if self._inflight and (
                spec_tick or constrained_live
                or any(t.kind == "spec"
                       for t in self._inflight)):
            # Either the NEXT tick needs settled commit state (it is
            # speculative), or an in-flight one is speculative (its
            # commits gate every later dispatch). Harvest, then
            # re-evaluate: the stream may have finished rows or flipped
            # the owe-fallback state.
            await self._pipeline_barrier(loop)
            decodable = self._decodable()
            if not decodable:
                return
            spec_tick = want_spec()
        if spec_tick:
            if self._paged:
                for i in decodable:
                    req = self._slot_state[i].request
                    # Lookahead only for rows that will actually
                    # speculate — a sampled or opted-out row writes one
                    # real token per tick and needs no window blocks.
                    # (_alloc_lookahead never preempts, so no barrier.)
                    if req.temperature <= 0 and req.speculate:
                        self._alloc_lookahead(i)
            with span("spec_tick", active=self.active_slots,
                      k=self.spec_k):
                self._inflight.append(await self._dispatch(
                    loop, self._spec_dispatch))
        else:
            with span("decode_tick", active=self.active_slots):
                self._inflight.append(await self._dispatch(
                    loop, self._decode_dispatch))
            # Harvest the oldest tick(s) past the in-flight window,
            # with the newest already on the device: the one D2H waits
            # for the oldest only; everything after it overlaps the
            # later ticks. Depth<=1 keeps at most ONE tick in flight
            # (the PR-14 overlap); depth>1 on a pp mesh keeps up to
            # ``depth`` micro-batch ticks flowing through the stages.
            while len(self._inflight) > max(1, self.pipeline_depth):
                await self._complete_tick(loop, self._inflight.popleft())
        if self._arm_after_warmup and self.auditor is not None:
            # The first dispatch IS the warmup: compilation is
            # synchronous at the jit call (only execution is async), so
            # every executable exists now (the ctor pre-compiled the
            # spec trio) and every later compile is a violated
            # invariant.
            self._arm_after_warmup = False
            self.auditor.arm(*self._decode_audit_names)
        if self.pipeline_depth == 0:
            await self._pipeline_barrier(loop)

    async def _dispatch(self, loop, fn) -> _InflightTick:
        """Run one tick dispatch. The first ever goes to the executor
        (it compiles — seconds the event loop must stay responsive
        through); warm dispatches run inline on the loop thread, where
        their only cost is arg prep + the async enqueue — saving the
        executor round trip that would otherwise serialize every tick
        behind a thread hop."""
        if self._dispatch_warm:
            return fn()
        tick = await self._in_executor(loop, fn)
        self._dispatch_warm = True
        return tick

    async def _pipeline_barrier(self, loop) -> None:
        """Drain the pipeline: harvest, stream, and tear down the
        in-flight tick (if any). Called before every event that mutates
        batch shape or content — admission, chunked-prefill progress,
        paged growth/preemption, param swap, KV transfer, cancel/expire
        teardown, idle, shutdown — and as the depth-0 serializer. Under
        depth>1 this drains ALL stages' in-flight micro-batch ticks,
        oldest first."""
        while self._inflight:
            await self._complete_tick(loop, self._inflight.popleft())

    async def _complete_tick(self, loop, tick: _InflightTick) -> None:
        """Harvest one dispatched tick and do its host half: stream the
        committed tokens of every row that was decodable at dispatch and
        is still alive, then tear down rows that finished. A row whose
        slot emptied between dispatch and harvest (a finish processed
        while the next tick was already in flight) is dropped exactly
        like a mid-prefill garbage row."""
        # Readiness fast path: when the device already finished the
        # tick (the pipelined steady state — the whole host iteration
        # ran while it computed), the harvest is a ready-buffer memcpy
        # and the executor round trip would cost more than the read.
        # Only a harvest that would genuinely BLOCK takes the thread
        # hop, keeping the event loop responsive through real waits.
        if tick.kind == "spec":
            if _tick_ready(tick):
                out, commit, caps = self._harvest_spec(tick)
            else:
                out, commit, caps = await self._in_executor(
                    loop, self._harvest_spec, tick)
            self._spec_owe_fallback = any(
                int(commit[i]) == 0 for i in tick.rows
                if self._slot_state[i] is not None)
        else:
            if _tick_ready(tick):
                nxt = self._harvest_decode(tick)
            else:
                nxt = await self._in_executor(
                    loop, self._harvest_decode, tick)
            self._spec_owe_fallback = False
        t = time.monotonic()
        with span("stream", active=self.active_slots):
            for i in tick.rows:
                st = self._slot_state[i]
                if st is None or st.prefill is not None:
                    # The slot emptied (or was recycled into a new
                    # prefill) since dispatch: this tick's row output is
                    # speculative garbage.
                    continue
                if tick.kind == "spec":
                    self._stream_spec(st, out[i], int(commit[i]),
                                      int(caps[i]), t)
                else:
                    self._push_token(st, int(nxt[i - tick.mb_start]), t)
                if st.remaining == 0:
                    if self._paged:
                        # Still-dispatched later tick(s) optimistically
                        # advanced this slot's watermark; the request is
                        # finished, so roll every such advance back
                        # BEFORE adoption — the trie must never claim an
                        # in-flight speculative write (its block is
                        # freed instead, and the write lands before any
                        # barrier-gated reuse can touch it).
                        for later in self._inflight:
                            if i in later.advanced:
                                self._lens[i] -= 1
                                later.advanced.discard(i)
                                self._positions_dirty = True
                    if st.fork_idx is not None:
                        self._finish_fork_row(i, st)
                    else:
                        self._finish_ok(st.request)
                        self._free_slot_paged(i, st)
                        self._slot_state[i] = None

    # -- internals ----------------------------------------------------------
    @staticmethod
    def _in_executor(loop, fn, *args):
        """run_in_executor with contextvars propagated (it doesn't, unlike
        asyncio.to_thread) so telemetry spans opened in the executor
        thread parent correctly to the loop-side span that dispatched
        them. copy_context() is copy-on-write — negligible per-call."""
        ctx = contextvars.copy_context()
        return loop.run_in_executor(None, lambda: ctx.run(fn, *args))

    @staticmethod
    def _pow2_fit(P: int, room: int) -> int:
        """Shrink a pad width to the largest power of two that fits the
        remaining cache room (the prefill overshoot guard — see
        :meth:`_prefill_step` for why overshooting would clamp the KV
        write backward over real rows). Shared by the target and draft
        prefill chunkers so the bound can never drift between them."""
        if P > room:
            P = 1
            while P * 2 <= room:
                P *= 2
        return P

    def _bucket(self, n: int, cap: int | None = None) -> int:
        """Prefill pad length: next power of two >= n (>= min bucket),
        capped at the decodable context (and at ``cap`` — the chunk size,
        for a ragged final chunk) — bounds prefill compiles at
        log2(context) programs total."""
        b = self._min_bucket
        while b < n:
            b *= 2
        return min(b, self.limit if cap is None else min(cap, self.limit))

    def _release_prefill(self, st: _SlotState) -> None:
        """Drop a slot's pending prefill (cancel/expiry/shutdown paths):
        unpin its prefix-cache match so the blocks become evictable."""
        if st.prefill is not None:
            if self.prefix_cache is not None:
                self.prefix_cache.release(st.prefill.match)
            st.prefill = None

    def _route_admission(self, st: _SlotState, slot: int, tok0) -> None:
        """Dispatch a completed prefill to its kind's finisher: plain
        int first token → decode admission; scorelike sentinel →
        prefill-only completion; fork tokens → fan-out."""
        if tok0 is _SCORELIKE_DONE:
            self._finish_scorelike(st, slot)
        elif isinstance(tok0, _ForkReady):
            self._finish_fork(st, slot, tok0.tokens)
        else:
            self._finish_admission(st, slot, tok0)

    def _scorelike_chunk(self, st: _SlotState, slot: int, padded,
                         c: int, s0: int) -> None:
        """One score/embed prefill chunk (executor thread): the same
        paged KV writes as a prefill chunk with the kind's epilogue
        accumulated host-side — per-position next-token logprobs for
        score, the hidden-state sum for embed."""
        job, req = st.prefill, st.request
        hg = self.metrics.host_gap
        table_row = jnp.asarray(self._tables[slot])
        if req.kind == "score":
            targets = np.zeros((padded.shape[1],), np.int32)
            for j in range(c):
                p = job.pos + j + 1
                if p < s0:
                    targets[j] = req.prompt[p]
            self._cache, picked = self._score_chunk(
                self._params, self._cache, jnp.asarray(padded),
                jnp.int32(job.pos), jnp.int32(c), table_row,
                jnp.asarray(targets))
            hg.harvest_started()
            vals = np.asarray(picked)
            hg.harvest_ended()
            if st.score_acc is None:
                st.score_acc = []
            for j in range(c):
                if job.pos + j + 1 < s0:
                    st.score_acc.append(float(vals[j]))
        else:
            self._cache, vec = self._embed_chunk(
                self._params, self._cache, jnp.asarray(padded),
                jnp.int32(job.pos), jnp.int32(c), table_row)
            hg.harvest_started()
            v = np.asarray(vec, dtype=np.float64)
            hg.harvest_ended()
            st.embed_acc = (v if st.embed_acc is None
                            else st.embed_acc + v)

    def _finish_scorelike(self, st: _SlotState, slot: int) -> None:
        """Complete a prefill-only (score/embed) request: publish its
        result on the Request, adopt the prompt's KV into the prefix
        trie (future generates over the same prompt hit it), and free
        the slot — it never entered the decodable set."""
        req = st.request
        t = time.monotonic()
        req.t_first_token = t
        self.metrics.record_first_token(t - req.t_submit,
                                        trace_id=req.trace_id)
        if req.kind == "score":
            req.logprobs = list(st.score_acc or [])
        else:
            s0 = max(1, len(req.prompt))
            vec = (st.embed_acc if st.embed_acc is not None
                   else np.zeros((1,), np.float64))
            req.embedding = [float(v) / s0 for v in vec]
        self._finish_ok(req)
        self._free_slot_paged(slot, st)
        self._slot_state[slot] = None

    def _finish_fork(self, st: _SlotState, slot: int, toks: list) -> None:
        """Fan a completed fork-parent prefill out to its n rows (loop
        thread; async device dispatches only). The prompt's COMPLETE
        blocks are shared copy-on-write through pool refcounts
        (:meth:`KVBlockPool.fork`); a partially filled tail block is the
        one divergent-write site at fork time, so it is eagerly copied
        per child (gather → scatter, counted as a CoW copy). Each row
        then owns its table row, sampling state, and private token
        stream; the DONE frame carries all n completions."""
        req = st.request
        pool = self.kv_pool
        bt = self.kv_block_tokens
        s0 = len(req.prompt)
        n = req.n
        children = [i for i, s in enumerate(self._slot_state)
                    if s is not None and s.fork_wait and s.request is req]
        complete = s0 // bt
        partial = s0 % bt
        shared = [int(b) for b in self._tables[slot][:complete]]
        if shared and children:
            pool.fork(shared, n)
            self.metrics.record_fork_blocks((n - 1) * len(shared))
        tail_data = None
        if partial and children:
            parent_tail = int(self._tables[slot][complete])
            tail_data = self._kv_gather(
                self._cache, jnp.asarray([parent_tail], jnp.int32))
        t = time.monotonic()
        req.t_first_token = t
        self.metrics.record_first_token(t - req.t_submit,
                                        trace_id=req.trace_id)
        rows = [(slot, st)] + [(c, self._slot_state[c])
                               for c in children]
        dry = False
        for k, (i, row_st) in enumerate(rows):
            row_st.fork_idx = k
            row_st.fork_tokens = [int(toks[k])]
            row_st.fork_wait = False
            row_st.last_token_t = t
            row_st.remaining = req.max_new_tokens - 1
            if i != slot:
                table = self._tables[i]
                table[:] = self._sentinel
                table[:complete] = shared
                row_st.blocks = list(shared)
                if partial:
                    ids = pool.alloc(1)
                    if ids is None:
                        dry = True
                        break
                    table[complete] = ids[0]
                    row_st.blocks.append(int(ids[0]))
                    self._cache = self._kv_scatter(
                        self._cache, tail_data,
                        jnp.asarray([int(ids[0])], jnp.int32))
                    pool.note_cow_copy()
                self._lens[i] = s0
            with span("cache_admit", slot=i):
                self._tokens, self._temps = self._admit_jit(
                    self._tokens, self._temps, jnp.int32(i),
                    jnp.int32(int(toks[k])),
                    jnp.float32(req.temperature))
        self._mark_tables_dirty()
        if dry:
            # Pool dry mid-fan-out (the admission precheck bounds the
            # completion footprint, not a racing peer's growth): error
            # the whole group typed, never a partial fork.
            self.metrics.record_oom_reject()
            self._finish_error(req, PoolExhausted(
                "KV pool exhausted during fork fan-out"))
            self._teardown_fork(req)
            return
        if req.trace is not None:
            req.trace.event("fork", n=n, shared_blocks=len(shared),
                            cow_copies=(n - 1) if partial else 0)
        if req.max_new_tokens <= 1:
            for i, row_st in rows:
                self._finish_fork_row(i, row_st)

    def _finish_fork_row(self, i: int, st: _SlotState) -> None:
        """One fork row finished: bank its completion; the LAST row to
        finish resolves the shared request (one DONE with all n)."""
        req = st.request
        req.fork_completions[st.fork_idx] = list(st.fork_tokens or [])
        self._free_slot_paged(i, st, adopt=False)
        self._slot_state[i] = None
        if all(c is not None for c in req.fork_completions):
            self._finish_ok(req)

    def _teardown_fork(self, req: Request) -> None:
        """Free every slot of a fork group (error paths): shared blocks
        drop one refcount per row, so the pool drains exactly."""
        for i, s in enumerate(self._slot_state):
            if s is not None and s.request is req:
                self._free_slot_paged(i, s, adopt=False)
                self._slot_state[i] = None

    def _finish_admission(self, st: _SlotState, slot: int, tok0: int) -> None:
        """Loop-thread bookkeeping once a slot's prefill completed: stream
        the first token (TTFT stamp — unless this is a preempted request
        resuming, whose TTFT already happened on its first admission) and
        free the slot if one token was all the request wanted."""
        t = time.monotonic()
        if st.request.t_first_token is None:
            self._push_token(st, tok0, t, first=True)
            st.remaining -= 1
        else:
            # Resumed after preemption: the prefill over prompt + already
            # -streamed tokens sampled the next CONTINUATION token.
            # _push_token(first=False) decrements remaining itself.
            self._push_token(st, tok0, t)
        if st.remaining == 0:
            self._finish_ok(st.request)
            self._free_slot_paged(slot, st)
            self._slot_state[slot] = None

    def _begin_prefill(self, req: Request) -> _PrefillJob:
        """Start a prompt's prefill (executor thread, DENSE mode): build
        the single-row cache — on a prefix-cache hit, materialized
        straight from the matched pool blocks (the covered leaves are
        never first built as zeros and re-written; see
        PrefixCache.materialize), on a miss from the jitted zeros
        factory. The uncached tail runs through :meth:`_prefill_step`
        chunk by chunk."""
        match, matched = None, 0
        if self.prefix_cache is not None:
            match = self.prefix_cache.match(req.prompt)
            matched = match.matched_tokens
        if matched:
            with span("prefix_splice", blocks=len(match.ids),
                      tokens=matched):
                cache = self.prefix_cache.materialize(match.ids)
        else:
            cache = self._fresh_row_cache()
        if req.trace is not None and matched:
            req.trace.event("prefix_splice", tokens=matched,
                            blocks=len(match.ids))
        return _PrefillJob(cache=cache, pos=matched, match=match,
                           matched_tokens=matched)

    def _prefill_step(self, st: _SlotState, slot: int) -> int | None:
        """Run ONE prefill chunk for the slot (executor thread; device
        work only). Returns None while the prompt is still incomplete;
        on the final chunk, DENSE mode stores the prompt's new blocks
        into the prefix cache and splices the finished single-row cache
        into batch row ``slot``, while PAGED mode has nothing to move —
        the chunks already wrote into the slot's pool blocks — and only
        the sampling state (first token, temperature) is set. Either way
        the request's first token comes back."""
        req, job = st.request, st.prefill
        tokens = self._resident_tokens(req)
        s0 = len(tokens)
        rem = s0 - job.pos
        c = rem if self._chunk is None else min(self._chunk, rem)
        if self._chunk is None:
            P = self._bucket(c)
        elif c == self._chunk:
            P = self._chunk  # full chunk: ONE fixed-size program
        else:
            P = self._bucket(c, cap=self._chunk)  # ragged final chunk
        # The pad width must never overshoot the cache: with job.pos + P
        # > cache length the dense per-slot KV write would clamp its
        # start backward (bert.py's OOB discipline) and silently
        # overwrite the spliced prefix rows (paged writes past the table
        # are dropped, but the bound keeps the compile set shared).
        # Rather than compiling a bespoke non-power-of-two width per
        # matched length, shrink to the largest power of two that fits
        # and let the NEXT call(s) finish the remainder — the compile
        # set stays pow2-bounded and no token is prefilled twice.
        # (Monolithic admission loops on this method until it returns a
        # token, so near-context-limit prompts just take an extra
        # sub-chunk or two.)
        room = self._cache_len - job.pos
        if P > room:
            P = self._pow2_fit(P, room)
            c = min(c, P)  # room >= rem >= 1, so P >= 1 and c >= 1
        padded = np.zeros((1, P), np.int32)
        padded[0, :c] = tokens[job.pos:job.pos + c]
        self._key, sub = jax.random.split(self._key)
        temp = jnp.float32(req.temperature)
        t0 = time.monotonic()
        # The chunk counts in the host-gap tracker as dispatched device
        # work: without this, admission phases would book their (device-
        # busy) prefill time as "device idle" in the gap window between
        # a decode harvest and the next decode dispatch.
        hg = self.metrics.host_gap
        final = job.pos + c >= s0
        special = None
        with span("prefill", bucket=P, offset=job.pos, prompt_len=s0):
            if self._paged and req.kind in SCORELIKE_KINDS:
                # Prefill-only kinds: same KV writes, different epilogue
                # (per-token logprobs / hidden-state sum) accumulated
                # host-side per chunk.
                tok = tok0 = None
                self._scorelike_chunk(st, slot, padded, c, s0)
                hg.tick_dispatched()
            elif self._paged and final and (req.kind == "sample"
                                            or st.dfa is not None):
                # The final chunk hands the LOGITS row back instead of a
                # sampled token: the fork fan-out samples n first tokens
                # from it; constrained admission masks it first.
                self._cache, logits = self._prefill_logits(
                    self._params, self._cache, jnp.asarray(padded),
                    jnp.int32(job.pos), jnp.int32(c),
                    jnp.asarray(self._tables[slot]))
                hg.tick_dispatched()
                hg.harvest_started()
                if req.kind == "sample":
                    self._key, sub = jax.random.split(self._key)
                    temps_n = jnp.full((req.n,), req.temperature,
                                       jnp.float32)
                    forks = self._fork_sample(logits, temps_n, sub)
                    special = _ForkReady(
                        [int(t) for t in np.asarray(forks)])
                    tok = tok0 = None
                else:
                    row = (np.asarray(logits)
                           + st.dfa.mask_row(st.dfa_state,
                                             self._cfg.vocab_size))
                    if req.temperature > 0:
                        z = row.astype(np.float64) / req.temperature
                        z -= z.max()
                        p = np.exp(z)
                        p /= p.sum()
                        rng = np.random.default_rng(
                            int(np.asarray(sub)[0]))
                        tok0 = int(rng.choice(row.shape[0], p=p))
                    else:
                        tok0 = int(np.argmax(row))
                    tok = jnp.int32(tok0)
                hg.harvest_ended()
            elif self._paged:
                self._cache, tok = self._prefill(
                    self._params, self._cache, jnp.asarray(padded),
                    jnp.int32(job.pos), jnp.int32(c),
                    jnp.asarray(self._tables[slot]), temp, sub)
                hg.tick_dispatched()
                hg.harvest_started()
                tok0 = int(tok)  # blocks: honest device time per chunk
                hg.harvest_ended()
            else:
                job.cache, tok = self._prefill(
                    self._params, job.cache, jnp.asarray(padded),
                    jnp.int32(job.pos), jnp.int32(c), temp, sub)
                hg.tick_dispatched()
                hg.harvest_started()
                tok0 = int(tok)  # blocks: honest device time per chunk
                hg.harvest_ended()
        chunk_s = time.monotonic() - t0
        job.device_s += chunk_s
        job.chunks_done += 1
        if req.trace is not None:
            req.trace.event("prefill_chunk", offset=job.pos, tokens=c,
                            bucket=P, dur_s=round(chunk_s, 9))
        job.pos += c
        if self._paged:
            # Written-KV watermark: a preemption between chunks adopts /
            # frees exactly the positions written so far.
            self._lens[slot] = job.pos
        if job.pos < s0:
            return None
        # Prompt complete.
        if req.kind in SCORELIKE_KINDS or special is not None:
            # score/embed never join the decodable set; a fork parent's
            # per-row admits happen at fan-out on the loop thread.
            self.metrics.record_prefill(
                job.device_s, job.chunks_done, job.matched_tokens, s0)
            req.prefill_device_s += job.device_s
            req.prefill_chunks += job.chunks_done
            req.prefix_hit_tokens = int(job.matched_tokens or 0)
            if req.trace is not None:
                req.trace.data.update(
                    prefill_device_s=round(job.device_s, 9),
                    prefill_chunks=job.chunks_done,
                    cache_hit_tokens=job.matched_tokens)
            st.prefill = None
            return special if special is not None else _SCORELIKE_DONE
        if self._paged:
            with span("cache_admit", slot=slot):
                self._tokens, self._temps = self._admit_jit(
                    self._tokens, self._temps, jnp.int32(slot), tok, temp)
            # The slot joins the decodable set: the masked table view
            # gains its row, so the next tick must re-upload.
            self._mark_tables_dirty()
        else:
            # Store the complete blocks this prefill computed (future
            # requests sharing the prefix hit them), then splice the row
            # into the live batch cache.
            if self.prefix_cache is not None:
                with span("prefix_insert", prompt_len=s0):
                    self.prefix_cache.insert(req.prompt, job.cache)
                self.prefix_cache.release(job.match)
            with span("cache_splice", slot=slot):
                self._cache, self._tokens, self._temps = self._admit_jit(
                    self._cache, self._tokens, self._temps, jnp.int32(slot),
                    job.cache, tok, temp)
        self.metrics.record_prefill(
            job.device_s, job.chunks_done,
            job.matched_tokens if (self._paged or
                                   self.prefix_cache is not None) else None,
            s0)
        if self._spec:
            # The draft's prompt K/V, built once the target prefill
            # finished (executor thread — the loop stays responsive).
            # After this the slot's fed-token truth is s0 for BOTH
            # models; the first spec tick picks it up from here.
            with span("draft_prefill", slot=slot, prompt_len=s0):
                self._draft_prefill_slot(slot, tokens)
            self._spec_pos[slot] = s0
        req.prefill_device_s += job.device_s
        req.prefill_chunks += job.chunks_done
        req.prefix_hit_tokens = int(job.matched_tokens or 0)
        if req.trace is not None:
            req.trace.data.update(
                prefill_device_s=round(job.device_s, 9),
                prefill_chunks=job.chunks_done,
                cache_hit_tokens=job.matched_tokens)
        st.prefill = None
        return tok0

    def _decodable(self) -> list[int]:
        """Slots whose row is live and past prefill — the rows whose
        tick output is streamed (everyone else decodes garbage)."""
        return [i for i in range(self.slots)
                if self._slot_state[i] is not None
                and self._slot_state[i].prefill is None
                and not self._slot_state[i].fork_wait]

    def _mark_tables_dirty(self) -> None:
        """A table row (or the decodable set) changed: the next dispatch
        must rebuild + re-upload both the masked device tables and the
        device positions vector (they share the gating — every event
        that mutates one invalidates the other's cached view)."""
        self._tables_dirty = True
        self._positions_dirty = True

    def _upload_tables(self, decodable):
        """Device view of the block tables, MASKED to the sentinel for
        rows that must not write (free slots, mid-prefill slots — their
        garbage output is discarded, and the dropped scatter guarantees
        it cannot scribble on live blocks the way the dense path lets a
        free row scribble on its own). Rebuilt + re-uploaded only when
        the dirty flag says the masked view could have changed — set at
        the sites that mutate a table row (admission reserve, growth,
        preemption, teardown) and at prefill completion (the decodable
        set grew) — NOT by an O(slots × blocks) compare every tick.
        (Safe to hold across ticks: the decode jits donate cache/tokens
        only.)"""
        if self._pp > 1:
            # pp callers outside _pp_decode_dispatch (the spec verify
            # chain) always run at mb_count==1, so micro-batch 0 IS the
            # whole slot batch.
            return self._pp_tables(0, decodable)
        if self._tables_dirty or self._tables_dev is None:
            tables = np.full_like(self._tables, self._sentinel)
            for i in decodable:
                tables[i] = self._tables[i]
            self._tables_dev = jnp.asarray(tables)
            self._tables_dirty = False
        return self._tables_dev

    def _upload_mask(self):
        """Device view of the per-slot token mask, re-uploaded only when
        a DFA advanced (or a constrained slot was torn down) since the
        last tick — the dirty-flag pattern the block tables use, so the
        steady state re-feeds the cached device array and the masked
        decode step stays at one executable. The upload is timed into
        ``mask_upload_seconds``."""
        if self._mask_dirty or self._mask_dev is None:
            t0 = time.monotonic()
            if self.mesh is not None:
                self._mask_dev = jax.device_put(self._mask_host,
                                                self._replicated)
            else:
                self._mask_dev = jnp.asarray(self._mask_host)
            self.metrics.record_mask_upload(time.monotonic() - t0)
            self._mask_dirty = False
        return self._mask_dev

    def _set_slot_mask(self, i: int, st: _SlotState) -> None:
        """Refresh slot ``i``'s mask row from its DFA state (no-op rows
        stay all-zero); clears the row for non-DFA slots."""
        if not self._constrained_mode:
            return
        if st is not None and st.dfa is not None:
            row = st.dfa.mask_row(st.dfa_state, self._cfg.vocab_size)
            self._mask_host[i, :] = row
            # Mask-upload attribution: this request's DFA advance is
            # what forces the next tick's re-upload.
            st.request.mask_uploads += 1
        else:
            self._mask_host[i, :] = 0.0
        self._mask_dirty = True

    def _pp_tables(self, mb: int, rows) -> list:
        """Per-STAGE committed device views of micro-batch ``mb``'s
        masked tables (same dirty gating as :meth:`_upload_tables`; the
        dirty flag invalidates every micro-batch's cached view, each
        rebuilt lazily at its next dispatch). Committing each copy to
        its stage's replicated layout keeps every stage-jit argument
        placement identical across rebuild and steady-state ticks — the
        source-consistency rule compile-count==1 per stage rests on."""
        if self._tables_dirty or self._tables_dev is None:
            self._tables_dev = [None] * self._mb_count
            self._tables_dirty = False
        if self._tables_dev[mb] is None:
            lo = mb * self._mb_size
            tables = np.full((self._mb_size, self._table_blocks),
                             self._sentinel, np.int32)
            for i in rows:
                tables[i - lo] = self._tables[i]
            self._tables_dev[mb] = [jax.device_put(tables, rep)
                                    for rep in self._stage_rep]
        return self._tables_dev[mb]

    def _pp_positions(self, mb: int, rows) -> list:
        """Per-stage committed positions vectors for micro-batch ``mb``
        (each stage's steady-state tick re-feeds its OWN returned
        vector; a dirty rebuild re-commits to every stage's layout)."""
        if self._positions_dirty or self._positions_dev is None:
            self._positions_dev = [None] * self._mb_count
            self._positions_dirty = False
        if self._positions_dev[mb] is None:
            lo = mb * self._mb_size
            positions = np.zeros((self._mb_size,), np.int32)
            for i in rows:
                positions[i - lo] = self._lens[i]
            self._positions_dev[mb] = [jax.device_put(positions, rep)
                                       for rep in self._stage_rep]
        return self._positions_dev[mb]

    def _decode_dispatch(self) -> _InflightTick:
        """Enqueue ONE plain decode tick (executor thread) and return
        WITHOUT waiting for the device: JAX dispatch is asynchronous, so
        the host is free the moment the work is queued. All host-side
        bookkeeping that the tick's outcome does NOT depend on happens
        here — position watermarks advance by exactly one per decodable
        row, recorded in ``advanced`` so a teardown detected while the
        tick is still in flight can roll its row back."""
        if self._pp > 1:
            return self._pp_decode_dispatch()
        self._key, sub = jax.random.split(self._key)
        rows = tuple(self._decodable())
        if self._paged:
            tables_dev = self._upload_tables(rows)
            if self._positions_dirty or self._positions_dev is None:
                positions = np.zeros((self.slots,), np.int32)
                for i in rows:
                    positions[i] = self._lens[i]
                # Sharded: commit the rebuilt vector to the replicated
                # layout the decode step's out_shardings pins — jit
                # cache entries key on actual argument shardings, so an
                # uncommitted host upload here would occupy a DIFFERENT
                # executable than the steady-state ticks that re-feed
                # the committed jit output (same reason the ctor
                # commits tokens/temps).
                if self.mesh is not None:
                    self._positions_dev = jax.device_put(
                        np.asarray(positions), self._replicated)
                else:
                    self._positions_dev = jnp.asarray(positions)
                self._positions_dirty = False
            if self._constrained_mode:
                mask_dev = self._upload_mask()
                self._cache, self._tokens, self._positions_dev = (
                    self._decode_step(
                        self._params, self._cache, self._tokens,
                        self._temps, self._positions_dev, tables_dev,
                        mask_dev, sub))
            else:
                self._cache, self._tokens, self._positions_dev = (
                    self._decode_step(
                        self._params, self._cache, self._tokens,
                        self._temps, self._positions_dev, tables_dev,
                        sub))
            # Each decodable row appends exactly one K/V vector (the
            # device advances its own positions copy identically).
            for i in rows:
                self._lens[i] += 1
        else:
            self._cache, self._tokens = self._decode_step(
                self._params, self._cache, self._tokens, self._temps, sub)
            if self._spec:
                for i in rows:
                    self._spec_pos[i] += 1
        t = self.metrics.host_gap.tick_dispatched()
        return _InflightTick(kind="decode", rows=rows, t_dispatch=t,
                             tokens=self._tokens, advanced=set(rows))

    def _to_stage(self, x, s):
        """Place a cross-stage value on stage ``s``'s replicated
        sharding. jax auto-transfers single-device arrays between
        1-device stages, but a committed tp-sharded array fed to a jit
        on a DISJOINT sub-mesh is a runtime placement error — so every
        stage-boundary handoff is placed explicitly. The target layout
        is identical every call, so the consumer jit still keys one
        cache entry."""
        return jax.device_put(x, self._stage_rep[s])

    def _pp_decode_dispatch(self) -> _InflightTick:
        """One micro-batch decode tick through the stage chain
        (executor thread). The micro-batch is picked round-robin,
        skipping to the next one with decodable rows (an all-idle
        engine still dispatches — the warmup path decodes garbage on
        whichever micro-batch the cursor is at, exactly like the
        unsharded warmup). Every stage's jit is dispatched back to
        back; jax chains them through the activation future, so the
        host returns after enqueueing all S programs and the device
        timeline is stage 0 → ... → stage S-1. With depth>1 the NEXT
        call dispatches the next micro-batch while these stages drain —
        stage s is busy with micro-batch m while stage s-1 runs m+1 —
        which is what turns the per-stage idle bubble into overlap."""
        self._key, sub = jax.random.split(self._key)
        decodable = self._decodable()
        mb, rows = self._mb_rr, ()
        for off in range(self._mb_count):
            cand = (self._mb_rr + off) % self._mb_count
            lo = cand * self._mb_size
            cand_rows = tuple(i for i in decodable
                              if lo <= i < lo + self._mb_size)
            if cand_rows:
                mb, rows = cand, cand_rows
                break
        self._mb_rr = (mb + 1) % self._mb_count
        lo = mb * self._mb_size
        last = self._pp - 1
        x = self._tokens[mb][:, None]
        if self._paged:
            tables = self._pp_tables(mb, rows)
            pos = self._pp_positions(mb, rows)
            new_pos = [None] * self._pp
            for s in range(last):
                self._cache[s], x, new_pos[s] = self._decode_steps[s](
                    self._params[s], self._cache[s],
                    self._to_stage(x, s), pos[s], tables[s])
            self._cache[last], nxt, new_pos[last] = (
                self._decode_steps[last](
                    self._params[last], self._cache[last],
                    self._to_stage(x, last),
                    self._temps[mb], pos[last], tables[last], sub))
            self._positions_dev[mb] = new_pos
            for i in rows:
                self._lens[i] += 1
        else:
            for s in range(last):
                self._cache[s][mb], x = self._decode_steps[s](
                    self._params[s], self._cache[s][mb],
                    self._to_stage(x, s))
            self._cache[last][mb], nxt = self._decode_steps[last](
                self._params[last], self._cache[last][mb],
                self._to_stage(x, last),
                self._temps[mb], sub)
            if self._spec:
                for i in rows:
                    self._spec_pos[i] += 1
        self._tokens[mb] = nxt
        t = self.metrics.host_gap.tick_dispatched()
        return _InflightTick(kind="decode", rows=rows, t_dispatch=t,
                             tokens=nxt, advanced=set(rows),
                             mb=mb, mb_start=lo)

    def _harvest_decode(self, tick: _InflightTick) -> np.ndarray:
        """The one D2H per plain tick (executor thread): blocks until
        the device finishes the tick, then hands its token vector to
        the loop thread for streaming."""
        hg = self.metrics.host_gap
        hg.harvest_started()
        nxt = np.asarray(tick.tokens)
        t = hg.harvest_ended()
        if self._pp > 1:
            self.metrics.bubble.record(tick.t_dispatch, t, self._pp)
        self._tick_log.append({
            "kind": tick.kind, "rows": len(tick.rows),
            "t_dispatch": tick.t_dispatch, "t_harvest": t,
            "harvest_wait_s": round(hg.last_harvest_wait, 9),
            "host_gap_s": round(hg.last_gap, 9),
        })
        return nxt

    def _decode_sync(self) -> np.ndarray:
        """Serialized dispatch + harvest: the ``pipeline_depth=0`` tick
        and the ctor-warmup / swap-rewarm path (both must complete on
        the spot — a rewarm's whole job is proving the step ran)."""
        return self._harvest_decode(self._decode_dispatch())

    # -- speculative decoding (draft/verify) --------------------------------
    def _spec_dispatch(self) -> _InflightTick:
        """Enqueue one speculative tick (executor thread; device work
        only): fixed-K greedy draft scan, ONE batched K-position verify,
        masked accept — returned as an :class:`_InflightTick` whose
        harvest reads ``out``/``commit`` off the device. Unlike a plain
        tick, NO position bookkeeping advances here: the advance is the
        commit count, which only the harvest knows — which is also why
        the run loop never dispatches past an unharvested spec tick
        (the next tick's positions depend on it). All shapes are static
        in ``spec_k``, so the armed compile-count==1 contract holds per
        callable no matter how acceptance varies."""
        self._key, sub = jax.random.split(self._key)
        decodable = self._decodable()
        spec_ok = np.zeros((self.slots,), bool)
        remaining = np.zeros((self.slots,), np.int32)
        positions = np.zeros((self.slots,), np.int32)
        prev = np.zeros((self.slots,), np.int32)
        for i in decodable:
            st = self._slot_state[i]
            spec_ok[i] = (st.request.temperature <= 0
                          and st.request.speculate)
            remaining[i] = st.remaining
            positions[i] = (self._lens[i] if self._paged
                            else self._spec_pos[i])
            # The token at position fed-1, for the draft's heal apply:
            # the resident sequence's second-to-last element (the last
            # one is the unfed feed token) — read directly rather than
            # materializing prompt+out (O(context) per tick). Admission
            # streams at least one token before the first tick, so the
            # element always exists.
            out_t = st.request.out_tokens
            if len(out_t) >= 2:
                prev[i] = out_t[-2]
            elif out_t:
                prev[i] = st.request.prompt[-1]
            else:
                prev[i] = st.request.prompt[-2 if len(
                    st.request.prompt) >= 2 else -1]
        start = jnp.asarray(positions)
        self._draft_cache, drafts = self._draft_step(
            self._draft_params, self._draft_cache, jnp.asarray(prev),
            self._tokens, start)
        if self._paged:
            # ``room`` doubles as the accounting cap: a commit clamped
            # by allocation pressure must not read as draft rejection
            # in the accept-rate metric.
            caps = np.zeros((self.slots,), np.int32)
            for i in decodable:
                caps[i] = self._spec_room(i)
            if self._constrained_mode:
                # Speculation under masks: forbidden drafts are
                # rejected BEFORE the verify can commit them — each
                # constrained greedy row's cap is clamped to the
                # DFA-valid prefix of its draft window (one host sync
                # of the drafts, only when a constrained row is live).
                # Sampled constrained rows cap at 0: their one-token
                # commit would come from UNMASKED verify logits, so
                # they are served by masked fallback ticks instead.
                drafts_host = None
                for i in decodable:
                    sti = self._slot_state[i]
                    if sti.dfa is None:
                        continue
                    if not spec_ok[i]:
                        caps[i] = 0
                        continue
                    if drafts_host is None:
                        drafts_host = np.asarray(drafts)
                    caps[i] = min(
                        int(caps[i]),
                        sti.dfa.valid_prefix(sti.dfa_state,
                                             drafts_host[i]))
            tables_dev = self._upload_tables(decodable)
            self._cache, self._tokens, out, commit = self._verify_step(
                self._params, self._cache, self._tokens, drafts,
                self._temps, jnp.asarray(spec_ok), jnp.asarray(remaining),
                jnp.asarray(caps), start, tables_dev, sub)
        else:
            caps = np.full((self.slots,), self.spec_k, np.int32)
            self._cache, self._tokens, out, commit = self._verify_step(
                self._params, self._cache, self._tokens, drafts,
                self._temps, jnp.asarray(spec_ok), jnp.asarray(remaining),
                start, sub)
        t = self.metrics.host_gap.tick_dispatched()
        return _InflightTick(kind="spec", rows=tuple(decodable),
                             t_dispatch=t, out=out, commit=commit,
                             caps=caps)

    def _harvest_spec(self, tick: _InflightTick):
        """Spec-tick harvest (executor thread): the one D2H reads the
        committed-token matrix and commit counts, then the position
        watermarks advance by each row's ACTUAL commit — the part a
        plain tick can do at dispatch and a spec tick cannot."""
        hg = self.metrics.host_gap
        hg.harvest_started()
        out = np.asarray(tick.out)
        commit = np.asarray(tick.commit)
        t = hg.harvest_ended()
        if self._pp > 1:
            self.metrics.bubble.record(tick.t_dispatch, t, self._pp)
        for i in tick.rows:
            if self._paged:
                self._lens[i] += int(commit[i])
            else:
                self._spec_pos[i] += int(commit[i])
        if self._paged:
            # Commits are variable-width: the cached device positions
            # no longer match _lens.
            self._positions_dirty = True
        self._tick_log.append({
            "kind": tick.kind, "rows": len(tick.rows),
            "t_dispatch": tick.t_dispatch, "t_harvest": t,
            "harvest_wait_s": round(hg.last_harvest_wait, 9),
            "host_gap_s": round(hg.last_gap, 9),
        })
        return out, commit, tick.caps

    def _spec_sync(self):
        """Serialized spec tick (ctor warmup / depth 0): dispatch and
        harvest on the spot. Returns ``(out, commit, caps)`` —
        ``out[i, :commit[i]]`` are slot ``i``'s committed tokens this
        tick (0..K for live greedy rows — 0 means every draft was
        rejected and the run loop owes the batch a fallback tick —
        exactly 1 for temperature>0 rows riding the same batch, 0 for
        garbage rows) and ``caps[i]`` is the draft budget the row
        REALLY had (spec_k, minus paged allocation pressure) for honest
        accept accounting."""
        return self._harvest_spec(self._spec_dispatch())

    def _spec_room(self, i: int) -> int:
        """Contiguous allocated K/V positions from slot ``i``'s write
        offset (capped at the window size): how many of this tick's
        window writes will actually land. The verify clamps the row's
        commit to this, so speculation under pool pressure degrades to
        fewer tokens per tick instead of committing tokens whose K/V
        the OOB-drop scatter discarded."""
        bt = self.kv_block_tokens
        lens = int(self._lens[i])
        blk = lens // bt
        allocated = 0
        while (blk < self._table_blocks
               and self._tables[i, blk] != self._sentinel):
            allocated += bt
            blk += 1
        return min(allocated - lens % bt, self.spec_k)

    def _alloc_lookahead(self, i: int) -> None:
        """Opportunistic pre-tick growth for a speculating slot: chain
        blocks so the whole K-wide verify window can land. Unlike
        :meth:`_ensure_tail_block` this NEVER preempts — a dry pool just
        shrinks the row's ``_spec_room`` (fewer tokens per tick), which
        beats evicting a peer for lookahead capacity that rejected
        drafts may never use. Extra blocks are reclaimed at teardown /
        preemption by the adopt watermark like any tail block."""
        st = self._slot_state[i]
        bt = self.kv_block_tokens
        last = (int(self._lens[i]) + self.spec_k - 1) // bt
        for blk in range(int(self._lens[i]) // bt,
                         min(last, self._table_blocks - 1) + 1):
            if self._tables[i, blk] != self._sentinel:
                continue
            ids = self.kv_pool.alloc(1)
            if ids is None:
                return
            self._tables[i, blk] = ids[0]
            st.blocks.extend(ids)
            self._mark_tables_dirty()

    def _draft_prefill_slot(self, slot: int, tokens) -> None:
        """Build the draft's prompt K/V for a freshly admitted slot
        (executor thread): pow2-bucketed chunks through the draft
        prefill program into a scratch row, then one splice into the
        batched draft cache. Runs once per admission, after the TARGET
        prefill completed — the draft is small, so this costs a fraction
        of the prefill the admission already paid; on a prefix-cache hit
        the draft recomputes its prompt K/V (the cache pools hold target
        K/V only; caching draft K/V would double trie bookkeeping to
        save work that is cheap by the draft's definition)."""
        row = self._fresh_draft_row()
        pos, s0 = 0, len(tokens)
        while pos < s0:
            c = s0 - pos
            P = self._pow2_fit(self._bucket(c), self._cache_len - pos)
            c = min(c, P)
            padded = np.zeros((1, P), np.int32)
            padded[0, :c] = tokens[pos:pos + c]
            row = self._draft_prefill(
                self._draft_params, row, jnp.asarray(padded),
                jnp.int32(pos), jnp.int32(c))
            pos += c
        self._draft_cache = self._draft_admit(
            self._draft_cache, jnp.int32(slot), row)

    # -- paged-KV internals (host bookkeeping; no device work) --------------
    @staticmethod
    def _resident_tokens(req: Request) -> list:
        """The slot's full resident sequence: prompt plus already-
        streamed tokens (a preempted request resumes with its output
        folded back in, so adoption keys, resume prefill, and block math
        must all see the SAME sequence). Skips the list copy when
        nothing has streamed."""
        return (req.prompt + req.out_tokens if req.out_tokens
                else req.prompt)

    def _blocks_for(self, first_token: int, last_token: int) -> int:
        """Blocks covering token positions [first_token, last_token]."""
        bt = self.kv_block_tokens
        return last_token // bt - first_token // bt + 1

    def _reserve_paged(self, req: Request, slot: int):
        """Admission-time reservation (loop thread; zero device work):
        pin the longest shared prefix chain, allocate private blocks for
        the rest of the prompt, and point the slot's block table at
        both. Returns ``(job, blocks, first_block, match)``, or None
        after parking the request (requeued at its class head) because
        the pool is dry and nobody strictly lower-priority is running.

        Admission only preempts STRICTLY lower-priority slots — an
        equal-priority preemption would let a full pool thrash between
        peers; growth (:meth:`_ensure_tail_block`) is the path that may
        preempt within a class, because there a slot is wedged without
        a block."""
        pool = self.kv_pool
        tokens = self._resident_tokens(req)
        if self.kv_tier is not None:
            # Host-tier re-admission BEFORE the match: blocks the trie
            # evicted (but the tier kept) scatter back H2D and then
            # count as the prefix hits they are. Eviction cascades from
            # the adopt are fine — they spill lower-value leaves. The
            # spill exemplar points at the admission that triggered it.
            self._tier_trace_id = req.trace_id
            try:
                self._readmit_from_tier(tokens, trace_id=req.trace_id)
            finally:
                self._tier_trace_id = None
        # Scorelike requests skip the prefix match: a matched prefix
        # skips its chunks' compute, and the whole point of a scoring
        # prefill is the per-position values that compute produces.
        match = pool.match(tokens if req.kind not in SCORELIKE_KINDS
                           else tokens[:0])
        m = match.matched_tokens
        first_block = m // self.kv_block_tokens
        needed = self._blocks_for(m, len(tokens) - 1)
        ids = pool.alloc(needed)
        while ids is None:
            # Fork and scorelike rows are never preemption victims: a
            # fork row's private stream cannot resume through the
            # requeue path, and a scorelike row's accumulator would be
            # silently truncated by a prefix-matched re-admission.
            victims = [
                (i, s) for i, s in enumerate(self._slot_state)
                if s is not None and s.request.priority > req.priority
                and s.request.kind == "generate"]
            if not victims:
                pool.release(match)
                self.scheduler.requeue(req)
                self._parked_at_version = pool.version
                self._parked_req = req
                return None
            i, _ = max(victims,
                       key=lambda v: (v[1].request.priority, v[1].t_admit))
            self._preempt_slot(i)
            ids = pool.alloc(needed)
        row = self._tables[slot]
        row[:] = self._sentinel
        row[:first_block] = match.ids
        row[first_block:first_block + needed] = ids
        self._mark_tables_dirty()
        self._lens[slot] = m
        if req.trace is not None and m:
            req.trace.event("prefix_splice", tokens=m, blocks=first_block)
        job = _PrefillJob(cache=None, pos=m, match=None, matched_tokens=m)
        return job, ids, first_block, match

    def _needs_tail_block(self, i: int) -> bool:
        """True when slot ``i``'s next write position crosses into an
        unallocated block. A row whose ``_lens`` already reached the
        table's capacity needs nothing: that is a finishing row's
        optimistic depth-1 advance (``submit`` caps prompt + max_new at
        the context limit, so the limit is only reached on a row's
        final tick) — its in-flight write landed in the LAST block and
        the pending harvest tears it down; indexing the table one past
        the end for it would be an engine-killing IndexError."""
        blk = int(self._lens[i]) // self.kv_block_tokens
        return (blk < self._table_blocks
                and self._tables[i, blk] == self._sentinel)

    def _ensure_tail_block(self, i: int) -> bool:
        """Pre-tick growth: make sure slot ``i``'s next write position
        has a block, preempting the lowest-priority youngest slot —
        itself included — when the pool is dry. Returns False when slot
        ``i`` itself was the fairest victim (it is gone; the tick runs
        without it)."""
        st = self._slot_state[i]
        if not self._needs_tail_block(i):
            return True
        blk = int(self._lens[i]) // self.kv_block_tokens
        ids = self.kv_pool.alloc(1)
        while ids is None:
            victims = [(j, s) for j, s in enumerate(self._slot_state)
                       if s is not None
                       and s.request.kind == "generate"]
            if not victims:
                # Only fork/scorelike rows are resident and the pool is
                # dry: the wedged row's whole request errors typed (a
                # fork row tears its group down with it) — there is no
                # preemptable generate slot to relieve the pressure.
                self.metrics.record_oom_reject()
                self._finish_error(st.request, PoolExhausted(
                    "KV pool exhausted with no preemptible slot"))
                if st.fork_idx is not None or st.fork_wait:
                    self._teardown_fork(st.request)
                else:
                    self._free_slot_paged(i, st, adopt=False)
                    self._slot_state[i] = None
                return False
            j, _ = max(victims,
                       key=lambda v: (v[1].request.priority, v[1].t_admit))
            self._preempt_slot(j)
            if j == i:
                return False
            ids = self.kv_pool.alloc(1)
        self._tables[i, blk] = ids[0]
        st.blocks.extend(ids)
        self._mark_tables_dirty()
        return True

    def _preempt_slot(self, i: int) -> None:
        """Evict slot ``i`` for its KV blocks and requeue its request at
        the front of its priority class (oversubscription's relief
        valve). The complete blocks of its written K/V are ADOPTED into
        the prefix trie — evictable if the pressure persists, but a
        prompt re-admission re-matches them and resumes nearly free —
        and its streamed tokens ride along in ``req.out_tokens``, so the
        resume prefill continues the sequence token-identically."""
        st = self._slot_state[i]
        req = st.request
        valid = int(self._lens[i])
        tokens = self._resident_tokens(req)
        self.kv_pool.adopt(tokens[:valid], st.blocks, st.first_block)
        self.kv_pool.release(st.match)
        st.blocks = []
        st.match = None
        st.prefill = None
        self._tables[i, :] = self._sentinel
        self._set_slot_mask(i, None)
        self._mark_tables_dirty()
        self._lens[i] = 0
        self._slot_state[i] = None
        self.metrics.record_preemption()
        req.preemptions += 1
        if req.trace is not None:
            req.trace.event("preempt", slot=i, resident_tokens=valid,
                            streamed=len(req.out_tokens))
        if self.flight_recorder is not None:
            self.flight_recorder.record_event(
                "preempt", trace_id=req.trace_id, slot=i)
        self.scheduler.requeue(req)

    def _free_slot_paged(self, i: int, st: _SlotState,
                         adopt: bool = True) -> None:
        """Slot teardown (paged mode; dense no-op): adopt the complete
        blocks of whatever K/V the slot computed into the prefix trie
        (zero-copy insert — a follow-up prompt sharing the prefix, or a
        multi-turn continuation sharing prompt+output, re-matches them),
        free the rest, and unpin the shared chain."""
        if self._spec:
            self._spec_pos[i] = 0
        if not self._paged:
            return
        self._set_slot_mask(i, None)
        req = st.request
        if st.fork_idx is not None or st.fork_wait:
            # Fork rows never adopt: their decoded tail lives in
            # st.fork_tokens (not req.out_tokens), so the adoption key
            # would be wrong — and their shared prompt blocks are
            # refcounted, freed for real only by the LAST row.
            adopt = False
        # Peak KV footprint for the wide event, captured before the
        # block list is cleared (fork rows accumulate across the group).
        req.kv_blocks = max(req.kv_blocks, len(st.blocks))
        valid = int(self._lens[i])
        if adopt and valid:
            tokens = self._resident_tokens(req)
            self.kv_pool.adopt(tokens[:valid], st.blocks, st.first_block)
        else:
            self.kv_pool.free(st.blocks)
        self.kv_pool.release(st.match)
        st.blocks = []
        st.match = None
        self._tables[i, :] = self._sentinel
        self._mark_tables_dirty()
        self._lens[i] = 0

    def _stream_spec(self, st: _SlotState, row_out, commit: int,
                     cap: int, t: float) -> None:
        """Stream one slot's committed tokens from a speculative tick
        and book the accept accounting. ``commit`` was clamped in-kernel
        to the row's remaining budget (and, paged, its allocated room —
        ``cap``), so the push loop can never overshoot
        ``max_new_tokens``. The tokens of one tick share a timestamp —
        they really did arrive together, which is what the inter-token
        histogram should say."""
        req = st.request
        if req.temperature <= 0 and req.speculate:
            # Drafts the row could actually have used: spec_k clamped
            # by BOTH its remaining budget and (paged) the allocated
            # room the commit was clamped to. Counting the clamped-away
            # drafts would dilute the accept rate with "request
            # finished" / "pool pressure" — neither is draft quality,
            # and the metric's whole job is to isolate draft quality.
            # Every committed token IS an accepted draft in this
            # design, so accepted == commit.
            usable = min(self.spec_k, st.remaining, cap)
            if usable > 0:
                st.spec_drafted += usable
                st.spec_accepted += commit
                req.spec_drafted += usable
                req.spec_accepted += commit
                self.metrics.record_spec(usable, commit,
                                         trace_id=req.trace_id)
                if req.trace is not None:
                    req.trace.data["spec_drafted"] = st.spec_drafted
                    req.trace.data["spec_accepted"] = st.spec_accepted
        for j in range(commit):
            self._push_token(st, int(row_out[j]), t)

    def _push_token(self, st: _SlotState, tok: int, t: float,
                    first: bool = False) -> None:
        req = st.request
        if first:
            req.t_first_token = t
            self.metrics.record_first_token(t - req.t_submit,
                                            trace_id=req.trace_id)
            if req.trace is not None:
                req.trace.event("first_token",
                                ttft_s=round(t - req.t_submit, 9))
        else:
            self.metrics.record_inter_token(t - st.last_token_t,
                                            trace_id=req.trace_id)
            st.remaining -= 1
        st.last_token_t = t
        if st.dfa is not None:
            # Advance the automaton host-side; reaching a terminal
            # state (no outgoing edges) force-finishes the request.
            nxt_state = st.dfa.step(st.dfa_state, tok)
            if nxt_state is None:
                st.remaining = 0
            else:
                st.dfa_state = nxt_state
                if st.dfa.is_terminal(st.dfa_state):
                    st.remaining = 0
            self._set_slot_mask(self._slot_state.index(st), st)
        if st.fork_tokens is not None:
            # Fork rows keep a private stream; the DONE frame carries
            # all n completions — nothing is streamed as token events.
            st.fork_tokens.append(tok)
            return
        req.out_tokens.append(tok)
        req.events.put_nowait(("token", tok))

    def _finish_ok(self, req: Request) -> None:
        if req.t_done is not None:
            # A fork group's n rows share one Request: only the first
            # terminal transition counts.
            return
        req.t_done = time.monotonic()
        self.scheduler.release_quota(req)
        self.metrics.record_finish(req.t_done - req.t_submit)
        done_tokens = (sum(len(c) for c in req.fork_completions)
                       if req.fork_completions is not None
                       else len(req.out_tokens))
        self.metrics.record_tenant_done(req.tenant, done_tokens)
        self._finalize_trace(req, "ok")
        done = {
            "tokens": done_tokens,
            "ttft_s": req.ttft,
            "latency_s": req.t_done - req.t_submit,
            "weight_version": req.weight_version,
            "tenant": req.tenant,
            "kind": req.kind,
        }
        if req.fork_completions is not None:
            done["completions"] = req.fork_completions
        if req.logprobs is not None:
            done["logprobs"] = req.logprobs
        if req.embedding is not None:
            done["embedding"] = req.embedding
        req.events.put_nowait(("done", done))
        req.done.set()

    def _finish_error(self, req: Request, err: ServingError) -> None:
        if req.t_done is not None:
            return
        req.error = err
        req.t_done = time.monotonic()
        # Quota credit on EVERY terminal path: a charged request that
        # expired in queue must hand its unused tokens back, or a
        # bursty tenant's failed work double-charges its budget.
        self.scheduler.release_quota(req)
        self._finalize_trace(req, err.code, message=str(err))
        req.events.put_nowait(("error", err))
        req.done.set()

    def _finalize_trace(self, req: Request, status: str,
                        message: str | None = None) -> None:
        """Terminal bookkeeping for one request: SLO verdict (counter
        even with tracing off), the wide-event append, and timeline
        finalization into the trace store / flight recorder. Cheap
        no-op when nothing is armed."""
        latency = (req.t_done - req.t_submit
                   if req.t_done is not None and req.t_submit is not None
                   else None)
        slow = (self.slo_s is not None and latency is not None
                and latency > self.slo_s)
        if slow:
            self.metrics.record_slo_violation()
        # The wide event is emitted BEFORE the trace-gated return: one
        # flat record per finished request regardless of whether
        # timelines are armed.
        if self.wide_events is not None:
            self._emit_wide_event(req, status, latency, slow)
        rec = req.trace
        if rec is None:
            return
        req.trace = None  # finalize exactly once
        if status == "ok":
            rec.event("done", tokens=len(req.out_tokens))
        else:
            rec.event("error", code=status,
                      message=(message or "")[:200] or None)
        d = rec.data
        d["status"] = status
        d["tenant"] = req.tenant
        d["tokens_out"] = len(req.out_tokens)
        d["prompt_tokens"] = len(req.prompt)
        if latency is not None:
            d["latency_s"] = round(latency, 9)
        if req.ttft is not None:
            d["ttft_s"] = round(req.ttft, 9)
        if "admit_iteration" in d:
            # Decode ticks this request lived through (its share of the
            # batch's iterations between admission and completion).
            d["decode_iterations"] = (self.metrics.iterations
                                      - d.pop("admit_iteration"))
        if slow:
            d["slo_violation"] = True
        recd = rec.to_dict()
        if self.trace_store is not None:
            self.trace_store.put(recd)
        if self.flight_recorder is not None:
            self.flight_recorder.record_timeline(recd, slow=slow)

    def _emit_wide_event(self, req: Request, status: str,
                         latency: float | None, slow: bool) -> None:
        """Assemble and append the one canonical flat record for a
        finished request — every column from state the engine already
        holds (no new per-token work anywhere feeds this; the counters
        are plain attribute writes at per-request events). Called once
        per request from the terminal path."""
        prov = req.weight_version or self.weight_version or {}
        forks = 0
        out_tokens = len(req.out_tokens)
        if req.fork_completions is not None:
            done_forks = [c for c in req.fork_completions if c is not None]
            forks = len(done_forks)
            out_tokens = sum(len(c) for c in done_forks)
        migration = ""
        kv_info = getattr(req, "kv_migration", None)
        if isinstance(kv_info, dict):
            migration = ("fallback" if kv_info.get("fallback")
                         else "imported")
        err_kind = ""
        if status != "ok":
            err_kind = (type(req.error).__name__
                        if req.error is not None else status)
        mesh_desc = ""
        if self.mesh is not None:
            mesh_desc = ",".join(f"{a}={int(s)}"
                                 for a, s in self.mesh.shape.items())
        record = {
            "trace_id": req.trace_id,
            "t_done": time.time(),
            "tenant": req.tenant,
            "kind": req.kind,
            "priority": req.priority,
            "replica": self.trace_source,
            "role": self.serve_role,
            "mesh": mesh_desc,
            "pp_depth": self._pp,
            "pp_stage": None,  # filled by per-stage launchers
            "weight_version": prov.get("version"),
            "weight_digest": prov.get("digest") or "",
            "prompt_tokens": len(req.prompt),
            "output_tokens": out_tokens,
            "max_new_tokens": req.max_new_tokens,
            "prefix_hit_tokens": req.prefix_hit_tokens,
            "kv_blocks": req.kv_blocks,
            "forks": forks,
            "n": req.n,
            "preemptions": req.preemptions,
            "migration": migration,
            "queue_wait_s": req.queue_wait_s,
            "prefill_device_s": (req.prefill_device_s
                                 if req.prefill_chunks else None),
            "prefill_chunks": req.prefill_chunks,
            "ttft_s": req.ttft,
            "latency_s": latency,
            "decode_iterations": (
                self.metrics.iterations - req.admit_iteration
                if req.admit_iteration is not None else None),
            "spec_drafted": req.spec_drafted,
            "spec_accepted": req.spec_accepted,
            "spec_accept_rate": (req.spec_accepted / req.spec_drafted
                                 if req.spec_drafted else None),
            "mask_uploads": req.mask_uploads,
            "constrained": int(req.constraint is not None),
            "cache_overtaken": int(req.cache_overtaken),
            "speculate": int(req.speculate),
            "temperature": req.temperature,
            "status": status,
            "error_kind": err_kind,
            "slo_verdict": "slow" if slow else "ok",
            "timeout_s": req.timeout,
            "stream": int(req.kind == "generate"),
        }
        self.wide_events.append(record)
