"""Client for the serving TCP protocol (see :mod:`.server` for the wire
format). Async-first with a sync convenience wrapper."""

from __future__ import annotations

import asyncio
import json
from typing import AsyncIterator, Callable, Sequence

from distkeras_tpu.serving.scheduler import (
    EngineStopped,
    QueueFullError,
    RequestTimeout,
    ServingError,
)

__all__ = ["ServingClient", "ServerError"]

_CODE_TO_ERROR = {
    "queue_full": QueueFullError,
    "timeout": RequestTimeout,
    "stopped": EngineStopped,
}


class ServerError(ServingError):
    """Server-side failure that has no more specific typed class."""

    code = "error"


def _raise_for(rec: dict) -> None:
    cls = _CODE_TO_ERROR.get(rec.get("code"), ServerError)
    raise cls(rec.get("error", "server error"))


class ServingClient:
    """One TCP connection; requests run sequentially per connection (open
    several clients for concurrency — the server batches across them)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8500):
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None

    async def connect(self) -> "ServingClient":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        return self

    async def aclose(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
            self._reader = self._writer = None

    async def __aenter__(self) -> "ServingClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.aclose()

    async def stream(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        priority: int = 0,
        timeout: float | None = None,
    ) -> AsyncIterator[int]:
        """Yield token ids as the server streams them; raises the typed
        :class:`ServingError` subclass matching the server's error code."""
        if self._writer is None:
            await self.connect()
        spec = {
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "priority": int(priority),
            "timeout": timeout,
        }
        self._writer.write((json.dumps(spec) + "\n").encode())
        await self._writer.drain()
        while True:
            line = await self._reader.readline()
            if not line:
                raise ConnectionError("server closed the connection")
            rec = json.loads(line)
            if "token" in rec:
                yield rec["token"]
            elif rec.get("done"):
                self.last_done = rec
                return
            else:
                _raise_for(rec)

    async def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        on_token: Callable[[int], None] | None = None,
        **kw,
    ) -> dict:
        """Collect a full generation; returns the server's ``done`` record
        (``tokens``, ``ttft_ms``, ``latency_ms``)."""
        async for tok in self.stream(prompt, max_new_tokens, **kw):
            if on_token is not None:
                on_token(tok)
        return self.last_done

    async def _control(self, spec: dict) -> dict:
        if self._writer is None:
            await self.connect()
        self._writer.write((json.dumps(spec) + "\n").encode())
        await self._writer.drain()
        line = await self._reader.readline()
        if not line:
            raise ConnectionError("server closed the connection")
        rec = json.loads(line)
        if "error" in rec:
            _raise_for(rec)
        return rec

    async def metricsz(self, format: str | None = None):
        """Scrape the server's live metrics registry: a nested dict by
        default, the Prometheus text page with ``format="prometheus"``."""
        spec = {"cmd": "metricsz"}
        if format is not None:
            spec["format"] = format
        return (await self._control(spec))["metricsz"]

    async def healthz(self) -> dict:
        """Engine liveness snapshot (slots, queue depth, compile count)."""
        return (await self._control({"cmd": "healthz"}))["healthz"]

    def generate_sync(self, prompt: Sequence[int], max_new_tokens: int,
                      **kw) -> dict:
        """Blocking one-shot convenience (opens and closes a connection)."""

        async def go():
            async with ServingClient(self.host, self.port) as c:
                return await c.generate(prompt, max_new_tokens, **kw)

        return asyncio.run(go())
