"""Client for the serving TCP protocol (see :mod:`.server` for the wire
format). Async-first with a sync convenience wrapper.

Speaks both front-door protocols:

- **jsonl** (default): the original newline-delimited JSON — one request
  in flight per connection, maximal compatibility;
- **bin1** (``wire="auto"`` / ``"bin1"``): the negotiated length-
  prefixed binary upgrade (:mod:`.wire`). The hello line is sent at
  connect; a peer that doesn't speak bin1 answers its normal
  unknown-verb ``bad_request`` and ``"auto"`` transparently downgrades
  to jsonl (``"bin1"`` raises instead — the strict mode tests use).
  bin1 connections are **multiplexed**: any number of :meth:`stream`
  calls may run concurrently on one connection, each under its own
  stream id — the client half of the router's 5x front door.
"""

from __future__ import annotations

import asyncio
import itertools
import json
from typing import AsyncIterator, Callable, Sequence

from distkeras_tpu.serving import wire
from distkeras_tpu.serving.scheduler import (
    EngineStopped,
    QueueFullError,
    RequestTimeout,
    ServingError,
    TenantOverQuota,
)
from distkeras_tpu.telemetry.request_trace import (new_trace_id,
                                                   sanitize_trace_id)

__all__ = ["ServingClient", "ServerError"]

_CODE_TO_ERROR = {
    "queue_full": QueueFullError,
    "timeout": RequestTimeout,
    "stopped": EngineStopped,
    "tenant_over_quota": TenantOverQuota,
}


class ServerError(ServingError):
    """Server-side failure that has no more specific typed class."""

    code = "error"


def _raise_for(rec: dict) -> None:
    cls = _CODE_TO_ERROR.get(rec.get("code"), ServerError)
    err = cls(rec.get("error", "server error"))
    # The wire code and trace id ride on the exception: a caller logging
    # a replica_lost failure can hand the id straight to `run.py debugz`
    # / the tracez verb without having kept the request spec around.
    err.code = rec.get("code", cls.code)
    err.trace_id = rec.get("trace_id")
    raise err


class ServingClient:
    """One TCP connection. On jsonl, requests run sequentially per
    connection (open several clients for concurrency — the server
    batches across them); on a negotiated bin1 connection, streams
    multiplex and any number may run concurrently.

    Idempotent control verbs (``metricsz``/``healthz``) transparently
    reconnect with capped exponential backoff when the connection drops —
    same shape as ``parallel/ha.py § RetryingClient`` — so a monitoring
    loop survives a server restart (or a replica bounce behind a router)
    instead of surfacing a raw ``ConnectionResetError``. ``max_retries``
    bounds the attempts, ``base_delay_s``/``max_delay_s`` the backoff;
    ``max_retries=0`` disables retry (health probes that must fail fast).
    Generation streams are NOT retried here: a reconnect would resubmit
    work whose first attempt may still be decoding — the cluster router
    owns that retry, where idempotence is provable.

    ``tenant`` is this client's QoS identity: stamped on every request
    spec (both protocols), it rides client -> router -> replica, keys
    the scheduler's fair queueing and quotas, and comes back on the done
    line. Per-call ``tenant=`` overrides it.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 8500, *,
                 max_retries: int = 3, base_delay_s: float = 0.1,
                 max_delay_s: float = 2.0, wire_mode: str = "jsonl",
                 tenant: str | None = None):
        if wire_mode not in ("jsonl", "auto", "bin1"):
            raise ValueError(f"wire_mode must be 'jsonl', 'auto' or "
                             f"'bin1', got {wire_mode!r}")
        self.host = host
        self.port = port
        self.wire_mode = wire_mode
        self.tenant = tenant
        self.max_retries = int(max_retries)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        # Trace id of the most recent stream() (error handlers and
        # monitoring wrappers read it unconditionally — it must exist
        # before the first request too).
        self.last_trace_id: str | None = None
        # The protocol this CONNECTION actually negotiated ("jsonl"
        # until a hello upgrade succeeds).
        self.proto: str = wire.PROTO_JSONL
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._sid = itertools.count(1)
        self._streams: dict[int, asyncio.Queue] = {}
        self._demux_task: asyncio.Task | None = None
        # Set when the bin1 demux loop dies (EOF/reset/corrupt frames):
        # later calls must raise ConnectionError IMMEDIATELY — writing
        # into a dead connection's buffer and awaiting a handler nobody
        # will ever call would hang forever, and the control verbs'
        # reconnect-with-backoff contract keys off the raised OSError.
        self._conn_lost = False

    async def connect(self) -> "ServingClient":
        # Generous line limit: a cluster router's aggregate metricsz
        # (every replica's registry snapshot on ONE line) outgrows
        # StreamReader's 64 KB default well before it stops being a
        # perfectly healthy reply.
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, limit=2**24)
        self.proto = wire.PROTO_JSONL
        self._conn_lost = False
        if self.wire_mode != "jsonl":
            self._writer.write(wire.hello_line())
            await self._writer.drain()
            line = await self._reader.readline()
            if not line:
                raise ConnectionError(
                    "server closed the connection during protocol "
                    "negotiation")
            try:
                rec = json.loads(line)
            except ValueError:
                rec = {}
            # An old server's unknown-verb bad_request lands here too:
            # parse_hello maps anything but an explicit bin1 selection
            # to jsonl — the downgrade IS the compatibility contract.
            self.proto = parse = wire.parse_hello(rec)
            if self.wire_mode == "bin1" and parse != wire.PROTO_BIN1:
                await self.aclose()
                raise ConnectionError(
                    f"peer refused the bin1 upgrade (offered {rec!r}) "
                    f"and wire='bin1' forbids the jsonl downgrade")
            if self.proto == wire.PROTO_BIN1:
                self._demux_task = asyncio.get_running_loop().create_task(
                    self._demux())
        return self

    async def aclose(self) -> None:
        if self._demux_task is not None:
            self._demux_task.cancel()
            try:
                await self._demux_task
            except (asyncio.CancelledError, Exception):
                pass
            self._demux_task = None
        self._fail_streams({"error": "connection closed", "code": "error"})
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
            self._reader = self._writer = None
        self.proto = wire.PROTO_JSONL

    async def __aenter__(self) -> "ServingClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.aclose()

    # -- bin1 demux ---------------------------------------------------------
    def _fail_streams(self, rec: dict) -> None:
        self._conn_lost = True
        streams, self._streams = self._streams, {}
        for handler in streams.values():
            try:
                # ftype None is the transport-failure event: distinct
                # from a server-sent T_ERR so readers surface
                # ConnectionError, not a typed serving error the server
                # never actually sent.
                handler(None, dict(rec))
            except Exception:
                pass  # one stream's cleanup must not strand the rest

    async def _demux(self) -> None:
        """Read frames off the negotiated bin1 connection and fan them
        out to the per-stream handlers (queue adapters for stream(),
        future resolvers for generate_batch()). A dead connection (EOF,
        reset, corrupt framing) fails every open stream with a typed
        error rather than hanging its reader."""
        decoder = wire.FrameDecoder()
        reader = self._reader
        try:
            while True:
                data = await reader.read(2 ** 18)
                if not data:
                    self._fail_streams({
                        "error": "server closed the connection",
                        "code": "error"})
                    return
                for ftype, sid, payload in decoder.feed(data):
                    handler = self._streams.get(sid)
                    if handler is None:
                        continue  # late frames of a cancelled stream
                    handler(ftype, payload)
        except asyncio.CancelledError:
            raise
        except (OSError, wire.WireError, ValueError) as e:
            self._fail_streams({"error": f"connection failed: {e}",
                                "code": "error"})

    def _spec(self, prompt, max_new_tokens, *, temperature, priority,
              timeout, speculate, tenant, kind="generate", n=1,
              constraint=None) -> dict:
        # Sanitize here too so last_trace_id matches the id the server
        # actually records (Request/router sanitize on their side).
        spec = {
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "priority": int(priority),
            "timeout": timeout,
            "trace_id": self.last_trace_id,
            "speculate": bool(speculate),
        }
        # Kind extras ride the bin1 extras whitelist, which drops falsy
        # values — only stamp them when they carry information, so a
        # plain generate encodes byte-identical to the pre-kinds wire.
        if kind and kind != "generate":
            spec["kind"] = str(kind)
        if n and int(n) > 1:
            spec["n"] = int(n)
        if constraint:
            spec["constraint"] = constraint
        tenant = tenant if tenant is not None else self.tenant
        if tenant:
            spec["tenant"] = str(tenant)
        return spec

    async def stream(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        priority: int = 0,
        timeout: float | None = None,
        trace_id: str | None = None,
        speculate: bool = True,
        tenant: str | None = None,
        kind: str = "generate",
        n: int = 1,
        constraint=None,
    ) -> AsyncIterator[int]:
        """Yield token ids as the server streams them; raises the typed
        :class:`ServingError` subclass matching the server's error code.

        ``trace_id`` is the request's distributed-trace identity: pass
        your own to correlate with caller-side logs, or let the client
        mint one (kept on :attr:`last_trace_id`). The same id tags every
        hop's spans and timeline records, rides back on the ``done`` /
        error line, and keys the ``tracez`` verb's merged trace."""
        if self._writer is None:
            await self.connect()
        self.last_trace_id = sanitize_trace_id(trace_id) or new_trace_id()
        spec = self._spec(prompt, max_new_tokens, temperature=temperature,
                          priority=priority, timeout=timeout,
                          speculate=speculate, tenant=tenant,
                          kind=kind, n=n, constraint=constraint)
        if self.proto == wire.PROTO_BIN1:
            async for tok in self._stream_bin1(spec):
                yield tok
            return
        self._writer.write((json.dumps(spec) + "\n").encode())
        await self._writer.drain()
        while True:
            line = await self._reader.readline()
            if not line:
                raise ConnectionError("server closed the connection")
            rec = json.loads(line)
            if "token" in rec:
                yield rec["token"]
            elif rec.get("done"):
                self.last_done = rec
                return
            else:
                _raise_for(rec)

    async def _stream_bin1(self, spec: dict) -> AsyncIterator[int]:
        """One multiplexed generation stream: REQ frame out, TOK deltas /
        DONE / ERR frames in on this stream's queue. An abandoned
        stream (caller stops iterating) sends a CANCEL frame so the
        server releases the slot — a mux peer can't signal by closing
        the shared connection."""
        if self._conn_lost:
            raise ConnectionError(
                "bin1 connection lost; reconnect before streaming")
        sid = next(self._sid)
        q: asyncio.Queue = asyncio.Queue()

        def handler(ftype, payload):
            if ftype is None:
                q.put_nowait(("lost", payload))
            elif ftype == wire.T_TOK:
                q.put_nowait(("tok", wire.decode_tokens(payload)))
            elif ftype == wire.T_DONE:
                q.put_nowait(("done", wire.decode_json(payload)))
            elif ftype in (wire.T_ERR, wire.T_CTRLR):
                q.put_nowait(("err" if ftype == wire.T_ERR else "ctrl",
                              wire.decode_json(payload)))

        self._streams[sid] = handler
        terminal = False
        try:
            self._writer.write(wire.encode_frame(
                wire.T_REQ, sid, wire.encode_request(spec)))
            await self._writer.drain()
            while True:
                kind, payload = await q.get()
                if kind == "tok":
                    for tok in payload:
                        yield tok
                elif kind == "done":
                    terminal = True
                    self.last_done = payload
                    return
                elif kind == "lost":
                    terminal = True
                    raise ConnectionError(payload.get(
                        "error", "connection failed"))
                else:
                    terminal = True
                    _raise_for(payload)
        finally:
            self._streams.pop(sid, None)
            if not terminal and self._writer is not None \
                    and not self._writer.is_closing():
                try:
                    self._writer.write(wire.encode_frame(
                        wire.T_CANCEL, sid, b""))
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass

    async def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        on_token: Callable[[int], None] | None = None,
        **kw,
    ) -> dict:
        """Collect a full generation; returns the server's ``done`` record
        (``tokens``, ``ttft_ms``, ``latency_ms``)."""
        async for tok in self.stream(prompt, max_new_tokens, **kw):
            if on_token is not None:
                on_token(tok)
        return self.last_done

    async def sample(self, prompt: Sequence[int], max_new_tokens: int,
                     n: int, **kw) -> dict:
        """Forked sampling: ONE prefill, ``n`` independent completions
        sharing the prompt's KV blocks copy-on-write. The done record's
        ``completions`` holds the ``n`` token lists."""
        return await self.generate(prompt, max_new_tokens,
                                   kind="sample", n=int(n), **kw)

    async def score(self, prompt: Sequence[int], **kw) -> dict:
        """Prefill-only scoring: the done record's ``logprobs`` holds the
        per-token log-probability of ``prompt[i+1]`` given the prefix
        (length ``len(prompt) - 1``); no decode slot is occupied."""
        return await self.generate(prompt, 0, kind="score", **kw)

    async def embed(self, prompt: Sequence[int], **kw) -> dict:
        """Prefill-only embedding: the done record's ``embedding`` holds
        the mean-pooled final hidden state over the prompt."""
        return await self.generate(prompt, 0, kind="embed", **kw)

    async def generate_batch(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        priority: int = 0,
        timeout: float | None = None,
        speculate: bool = True,
        tenant: str | None = None,
        kind: str = "generate",
        n: int = 1,
        constraint=None,
    ) -> list:
        """Submit MANY generations at once and await them all — the
        client half of batched admission. On a negotiated bin1
        connection every request rides one buffered write of REQ frames
        and resolves through a per-stream future (no token streaming, no
        per-request async generator — the cheapest possible path, which
        is what a throughput-bound caller wants); on jsonl it degrades
        to sequential :meth:`generate` calls. Returns a list aligned
        with ``prompts``: the done record per success, the typed
        exception per per-request failure (one rejected request must
        not fail its batchmates)."""
        if self._writer is None:
            await self.connect()
        if self.proto != wire.PROTO_BIN1:
            out: list = []
            for p in prompts:
                try:
                    out.append(await self.generate(
                        p, max_new_tokens, temperature=temperature,
                        priority=priority, timeout=timeout,
                        speculate=speculate, tenant=tenant,
                        kind=kind, n=n, constraint=constraint))
                except ServingError as e:
                    out.append(e)
            return out
        if self._conn_lost:
            raise ConnectionError("bin1 connection lost; reconnect "
                                  "before submitting a batch")
        loop = asyncio.get_running_loop()
        tenant = tenant if tenant is not None else self.tenant
        sids: list[int] = []
        entries: list = []  # a Future, or the per-item typed exception
        buf = bytearray()
        for p in prompts:
            spec = {
                "prompt": p, "max_new_tokens": int(max_new_tokens),
                "temperature": float(temperature),
                "priority": int(priority), "timeout": timeout,
                "speculate": bool(speculate),
            }
            if kind and kind != "generate":
                spec["kind"] = str(kind)
            if n and int(n) > 1:
                spec["n"] = int(n)
            if constraint:
                spec["constraint"] = constraint
            if tenant:
                spec["tenant"] = str(tenant)
            try:
                # Encode BEFORE registering anything: one unencodable
                # prompt must become its own slot in the result list,
                # never fail its batchmates or leak their handlers.
                payload = wire.encode_request(spec)
            except wire.WireError as e:
                entries.append(e)
                continue
            fut = loop.create_future()

            def handler(ftype, payload, fut=fut):
                if fut.done():
                    return
                if ftype == wire.T_DONE:
                    fut.set_result(wire.decode_json(payload))
                elif ftype is None:
                    fut.set_exception(ConnectionError(
                        (payload or {}).get("error",
                                            "connection failed")))
                elif ftype == wire.T_ERR:
                    try:
                        _raise_for(wire.decode_json(payload))
                    except ServingError as e:
                        fut.set_exception(e)
                # T_TOK deltas are skipped: the done record carries the
                # full token list, and this API is for callers that
                # want completions, not streams.

            sid = next(self._sid)
            self._streams[sid] = handler
            sids.append(sid)
            entries.append(fut)
            buf += wire.encode_frame(wire.T_REQ, sid, payload)
        try:
            if buf:
                self._writer.write(bytes(buf))
                await self._writer.drain()
            done = iter(await asyncio.gather(
                *(e for e in entries if isinstance(e, asyncio.Future)),
                return_exceptions=True))
            return [e if not isinstance(e, asyncio.Future) else next(done)
                    for e in entries]
        finally:
            for sid in sids:
                self._streams.pop(sid, None)

    async def _control_once(self, spec: dict) -> dict:
        if self._writer is None:
            await self.connect()
        if self.proto == wire.PROTO_BIN1:
            if self._conn_lost:
                # The demux loop died: raise the transport error NOW so
                # the retry wrapper reconnects, instead of registering a
                # handler nothing will ever call.
                raise ConnectionError("bin1 connection lost")
            sid = next(self._sid)
            fut = asyncio.get_running_loop().create_future()

            def handler(ftype, payload):
                if fut.done():
                    return
                if ftype is None:
                    fut.set_exception(ConnectionError(
                        (payload or {}).get("error", "connection failed")))
                else:
                    fut.set_result(wire.decode_json(payload))

            self._streams[sid] = handler
            try:
                self._writer.write(wire.encode_json_frame(
                    wire.T_CTRL, sid, spec))
                await self._writer.drain()
                rec = await fut
            finally:
                self._streams.pop(sid, None)
        else:
            self._writer.write((json.dumps(spec) + "\n").encode())
            await self._writer.drain()
            line = await self._reader.readline()
            if not line:
                raise ConnectionError("server closed the connection")
            rec = json.loads(line)
        if "error" in rec:
            _raise_for(rec)
        return rec

    async def _control(self, spec: dict, *, retry: bool = False) -> dict:
        """One control round trip. With ``retry`` (idempotent verbs only)
        a dropped/refused connection is retried over a FRESH connection
        with capped exponential backoff; server-side typed errors
        (:class:`ServingError`) always propagate immediately — only the
        transport is retried, never a server that answered."""
        if not retry or self.max_retries <= 0:
            return await self._control_once(spec)
        delay = self.base_delay_s
        last: BaseException | None = None
        for attempt in range(self.max_retries + 1):
            try:
                return await self._control_once(spec)
            except (OSError, ValueError) as e:
                # OSError covers ConnectionResetError/BrokenPipeError/
                # ConnectionRefusedError; ValueError covers the
                # JSONDecodeError of a reply truncated by a mid-write
                # server death. Either way the dead connection is
                # dropped so the next attempt dials fresh.
                last = e
                await self.aclose()
                if attempt < self.max_retries:
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, self.max_delay_s)
        raise ConnectionError(
            f"control verb {spec.get('cmd')!r} failed after "
            f"{self.max_retries + 1} attempts") from last

    async def metricsz(self, format: str | None = None):
        """Scrape the server's live metrics registry: a nested dict by
        default, the Prometheus text page with ``format="prometheus"``.
        Reconnects with backoff on a dropped connection (idempotent)."""
        spec = {"cmd": "metricsz"}
        if format is not None:
            spec["format"] = format
        return (await self._control(spec, retry=True))["metricsz"]

    async def healthz(self) -> dict:
        """Engine liveness snapshot (slots, queue depth, compile count).
        Reconnects with backoff on a dropped connection (idempotent)."""
        return (await self._control({"cmd": "healthz"},
                                    retry=True))["healthz"]

    async def debugz(self) -> dict:
        """Live introspection page: slot table, queue ages, prefix-cache
        trie occupancy (fleet-aggregated when pointed at a router).
        Reconnects with backoff on a dropped connection (idempotent)."""
        return (await self._control({"cmd": "debugz"},
                                    retry=True))["debugz"]

    async def tracez(self, trace_id: str | None = None, n: int = 20):
        """One request's timeline by trace id (a MERGED cross-process
        trace when pointed at a router), or the most recent ``n`` records
        with no id. Reconnects with backoff (idempotent)."""
        spec: dict = {"cmd": "tracez", "n": int(n)}
        if trace_id is not None:
            spec["trace_id"] = str(trace_id)
        return (await self._control(spec, retry=True))["tracez"]

    async def queryz(self, where=None, group_by=None, aggs=None,
                     max_groups: int | None = None) -> dict:
        """Wide-event analytics over the server's columnar per-request
        store (fleet-merged with bucket-exact percentiles when pointed
        at a router). ``where``: term strings like ``"kind=sample"`` /
        ``"ttft_s>0.25"``; ``group_by``: ≤2 column names; ``aggs``:
        specs like ``"count"`` / ``"mean:latency_s"`` / ``"p99:ttft_s"``.
        Reconnects with backoff (idempotent)."""
        spec: dict = {"cmd": "queryz"}
        if where:
            spec["where"] = [str(t) for t in where]
        if group_by:
            spec["group_by"] = [str(c) for c in group_by]
        if aggs:
            spec["aggs"] = [str(a) for a in aggs]
        if max_groups is not None:
            spec["max_groups"] = int(max_groups)
        return (await self._control(spec, retry=True))["queryz"]

    async def pin_traces(self, trace_ids) -> dict:
        """Pin trace ids never-evictable in the target's trace store
        (fans out fleet-wide through a router)."""
        ids = [str(t) for t in ([trace_ids] if isinstance(trace_ids, str)
                                else trace_ids)]
        return (await self._control({"cmd": "tracez", "pin": ids},
                                    retry=True))["tracez"]

    async def deployz(self) -> dict:
        """Continuous-deployment state (current / last-good / candidate
        versions, deploy history ring, quarantine records) from a router
        with an attached DeployController. Reconnects with backoff
        (idempotent)."""
        return (await self._control({"cmd": "deployz"},
                                    retry=True))["deployz"]

    async def reload(self, weights: str, timeout: float = 60.0,
                     migrate: bool = False) -> dict:
        """Hot-swap weights: a rolling reload when pointed at a cluster
        router, a single-engine swap when pointed at one server. NOT
        transport-retried (a retry could double-trigger a long rolling
        drain); callers handle ``ConnectionError`` themselves.

        ``migrate=True`` (router only): drain each replica by MIGRATING
        its live streams to peers (KV blocks pulled, streamed tokens
        folded into a resume) instead of waiting them out — long
        generations no longer hold the roll hostage. Migrated streams
        continue on whatever weights their new replica serves."""
        spec = {"cmd": "reload", "weights": weights, "timeout": timeout}
        if migrate:
            spec["migrate"] = True
        return (await self._control(spec))["reload"]

    def generate_sync(self, prompt: Sequence[int], max_new_tokens: int,
                      **kw) -> dict:
        """Blocking one-shot convenience (opens and closes a connection)."""

        async def go():
            async with ServingClient(self.host, self.port,
                                     wire_mode=self.wire_mode,
                                     tenant=self.tenant) as c:
                return await c.generate(prompt, max_new_tokens, **kw)

        return asyncio.run(go())
