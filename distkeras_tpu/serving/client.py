"""Client for the serving TCP protocol (see :mod:`.server` for the wire
format). Async-first with a sync convenience wrapper."""

from __future__ import annotations

import asyncio
import json
from typing import AsyncIterator, Callable, Sequence

from distkeras_tpu.serving.scheduler import (
    EngineStopped,
    QueueFullError,
    RequestTimeout,
    ServingError,
)
from distkeras_tpu.telemetry.request_trace import (new_trace_id,
                                                   sanitize_trace_id)

__all__ = ["ServingClient", "ServerError"]

_CODE_TO_ERROR = {
    "queue_full": QueueFullError,
    "timeout": RequestTimeout,
    "stopped": EngineStopped,
}


class ServerError(ServingError):
    """Server-side failure that has no more specific typed class."""

    code = "error"


def _raise_for(rec: dict) -> None:
    cls = _CODE_TO_ERROR.get(rec.get("code"), ServerError)
    err = cls(rec.get("error", "server error"))
    # The wire code and trace id ride on the exception: a caller logging
    # a replica_lost failure can hand the id straight to `run.py debugz`
    # / the tracez verb without having kept the request spec around.
    err.code = rec.get("code", cls.code)
    err.trace_id = rec.get("trace_id")
    raise err


class ServingClient:
    """One TCP connection; requests run sequentially per connection (open
    several clients for concurrency — the server batches across them).

    Idempotent control verbs (``metricsz``/``healthz``) transparently
    reconnect with capped exponential backoff when the connection drops —
    same shape as ``parallel/ha.py § RetryingClient`` — so a monitoring
    loop survives a server restart (or a replica bounce behind a router)
    instead of surfacing a raw ``ConnectionResetError``. ``max_retries``
    bounds the attempts, ``base_delay_s``/``max_delay_s`` the backoff;
    ``max_retries=0`` disables retry (health probes that must fail fast).
    Generation streams are NOT retried here: a reconnect would resubmit
    work whose first attempt may still be decoding — the cluster router
    owns that retry, where idempotence is provable.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 8500, *,
                 max_retries: int = 3, base_delay_s: float = 0.1,
                 max_delay_s: float = 2.0):
        self.host = host
        self.port = port
        self.max_retries = int(max_retries)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        # Trace id of the most recent stream() (error handlers and
        # monitoring wrappers read it unconditionally — it must exist
        # before the first request too).
        self.last_trace_id: str | None = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None

    async def connect(self) -> "ServingClient":
        # Generous line limit: a cluster router's aggregate metricsz
        # (every replica's registry snapshot on ONE line) outgrows
        # StreamReader's 64 KB default well before it stops being a
        # perfectly healthy reply.
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, limit=2**24)
        return self

    async def aclose(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
            self._reader = self._writer = None

    async def __aenter__(self) -> "ServingClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.aclose()

    async def stream(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        priority: int = 0,
        timeout: float | None = None,
        trace_id: str | None = None,
        speculate: bool = True,
    ) -> AsyncIterator[int]:
        """Yield token ids as the server streams them; raises the typed
        :class:`ServingError` subclass matching the server's error code.

        ``trace_id`` is the request's distributed-trace identity: pass
        your own to correlate with caller-side logs, or let the client
        mint one (kept on :attr:`last_trace_id`). The same id tags every
        hop's spans and timeline records, rides back on the ``done`` /
        error line, and keys the ``tracez`` verb's merged trace."""
        if self._writer is None:
            await self.connect()
        # Sanitize here too so last_trace_id matches the id the server
        # actually records (Request/router sanitize on their side).
        self.last_trace_id = sanitize_trace_id(trace_id) or new_trace_id()
        spec = {
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "priority": int(priority),
            "timeout": timeout,
            "trace_id": self.last_trace_id,
            "speculate": bool(speculate),
        }
        self._writer.write((json.dumps(spec) + "\n").encode())
        await self._writer.drain()
        while True:
            line = await self._reader.readline()
            if not line:
                raise ConnectionError("server closed the connection")
            rec = json.loads(line)
            if "token" in rec:
                yield rec["token"]
            elif rec.get("done"):
                self.last_done = rec
                return
            else:
                _raise_for(rec)

    async def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        on_token: Callable[[int], None] | None = None,
        **kw,
    ) -> dict:
        """Collect a full generation; returns the server's ``done`` record
        (``tokens``, ``ttft_ms``, ``latency_ms``)."""
        async for tok in self.stream(prompt, max_new_tokens, **kw):
            if on_token is not None:
                on_token(tok)
        return self.last_done

    async def _control_once(self, spec: dict) -> dict:
        if self._writer is None:
            await self.connect()
        self._writer.write((json.dumps(spec) + "\n").encode())
        await self._writer.drain()
        line = await self._reader.readline()
        if not line:
            raise ConnectionError("server closed the connection")
        rec = json.loads(line)
        if "error" in rec:
            _raise_for(rec)
        return rec

    async def _control(self, spec: dict, *, retry: bool = False) -> dict:
        """One control round trip. With ``retry`` (idempotent verbs only)
        a dropped/refused connection is retried over a FRESH connection
        with capped exponential backoff; server-side typed errors
        (:class:`ServingError`) always propagate immediately — only the
        transport is retried, never a server that answered."""
        if not retry or self.max_retries <= 0:
            return await self._control_once(spec)
        delay = self.base_delay_s
        last: BaseException | None = None
        for attempt in range(self.max_retries + 1):
            try:
                return await self._control_once(spec)
            except (OSError, ValueError) as e:
                # OSError covers ConnectionResetError/BrokenPipeError/
                # ConnectionRefusedError; ValueError covers the
                # JSONDecodeError of a reply truncated by a mid-write
                # server death. Either way the dead connection is
                # dropped so the next attempt dials fresh.
                last = e
                await self.aclose()
                if attempt < self.max_retries:
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, self.max_delay_s)
        raise ConnectionError(
            f"control verb {spec.get('cmd')!r} failed after "
            f"{self.max_retries + 1} attempts") from last

    async def metricsz(self, format: str | None = None):
        """Scrape the server's live metrics registry: a nested dict by
        default, the Prometheus text page with ``format="prometheus"``.
        Reconnects with backoff on a dropped connection (idempotent)."""
        spec = {"cmd": "metricsz"}
        if format is not None:
            spec["format"] = format
        return (await self._control(spec, retry=True))["metricsz"]

    async def healthz(self) -> dict:
        """Engine liveness snapshot (slots, queue depth, compile count).
        Reconnects with backoff on a dropped connection (idempotent)."""
        return (await self._control({"cmd": "healthz"},
                                    retry=True))["healthz"]

    async def debugz(self) -> dict:
        """Live introspection page: slot table, queue ages, prefix-cache
        trie occupancy (fleet-aggregated when pointed at a router).
        Reconnects with backoff on a dropped connection (idempotent)."""
        return (await self._control({"cmd": "debugz"},
                                    retry=True))["debugz"]

    async def tracez(self, trace_id: str | None = None, n: int = 20):
        """One request's timeline by trace id (a MERGED cross-process
        trace when pointed at a router), or the most recent ``n`` records
        with no id. Reconnects with backoff (idempotent)."""
        spec: dict = {"cmd": "tracez", "n": int(n)}
        if trace_id is not None:
            spec["trace_id"] = str(trace_id)
        return (await self._control(spec, retry=True))["tracez"]

    async def deployz(self) -> dict:
        """Continuous-deployment state (current / last-good / candidate
        versions, deploy history ring, quarantine records) from a router
        with an attached DeployController. Reconnects with backoff
        (idempotent)."""
        return (await self._control({"cmd": "deployz"},
                                    retry=True))["deployz"]

    async def reload(self, weights: str, timeout: float = 60.0) -> dict:
        """Hot-swap weights: a rolling reload when pointed at a cluster
        router, a single-engine swap when pointed at one server. NOT
        transport-retried (a retry could double-trigger a long rolling
        drain); callers handle ``ConnectionError`` themselves."""
        return (await self._control(
            {"cmd": "reload", "weights": weights,
             "timeout": timeout}))["reload"]

    def generate_sync(self, prompt: Sequence[int], max_new_tokens: int,
                      **kw) -> dict:
        """Blocking one-shot convenience (opens and closes a connection)."""

        async def go():
            async with ServingClient(self.host, self.port) as c:
                return await c.generate(prompt, max_new_tokens, **kw)

        return asyncio.run(go())
