"""Serving observability: latency-under-load metrics.

Training benchmarks in this repo measure *throughput* (samples/sec/chip,
``tracing.StepTimer``); a serving engine is judged on *latency under
load*: TTFT (time to first token), inter-token latency (decode-step
cadence), queue depth, slot occupancy, and goodput (tokens/sec actually
delivered). TTFT is recorded **split into its two causes** — admission
wait (``queue_wait``: submit → slot grant, the scheduler's doing) and
prefill device time (``prefill_device``: the chunks' compute, the
model's doing; any gap between the two in chunked mode is decode-tick
interleave) — because the operator response differs: queueing delay
wants more slots or load shedding, prefill cost wants a prefix cache or
smaller chunks. :class:`ServingMetrics`
accumulates those and emits structured records through the same
:class:`distkeras_tpu.tracing.MetricStream` JSONL sinks the trainers use;
:meth:`ServingMetrics.summary` follows ``StepTimer.summary``'s key
conventions (``*_p50_s`` etc.) with the tail percentiles (p95/p99) that
matter for serving SLOs.

Every event also publishes into a
:class:`~distkeras_tpu.telemetry.registry.MetricsRegistry` (counters for
request outcomes, histograms for the latency series, gauges for queue
depth / occupancy) — the registry is what the server's ``metricsz``
control verb scrapes live, and the percentile definition is the ONE
shared :func:`distkeras_tpu.telemetry.registry.percentile`.
"""

from __future__ import annotations

import collections
import time
from typing import Iterable

from distkeras_tpu.telemetry.registry import (
    MetricsRegistry,
    percentile as _percentile,
)
from distkeras_tpu.tracing import MetricStream

__all__ = ["BubbleTracker", "HostGapTracker", "ServingMetrics",
           "percentile"]

# Decode ticks and inter-token gaps sit well under the default buckets'
# upper range; keep a finer low end for them.
_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def percentile(values: Iterable[float], q: float) -> float:
    """Shared linear-interpolated percentile (``q`` in [0, 100]); see
    :func:`distkeras_tpu.telemetry.registry.percentile` — kept as a
    re-export because serving callers historically imported it here."""
    return _percentile(values, q)


class HostGapTracker:
    """Per-tick device-idle accounting for the decode pipeline.

    Three observable instants exist per tick: **dispatch** (the jit call
    returned — work is queued on the device), **harvest start** (the
    host began the tick's one D2H read) and **harvest end** (the read
    returned — the device has certainly finished the tick). From those:

    - ``host gap``: how long the device queue sat EMPTY before a
      dispatch. It is measurable exactly when the previous tick was
      already harvested (the queue has been empty since at latest that
      harvest's end): ``gap = max(0, t_dispatch - t_prev_harvest_end)``.
      When a tick is still in flight at dispatch time (the pipelined
      steady state) the queue was never empty and the gap is 0 by
      construction. At ``pipeline_depth=0`` — harvest immediately after
      every dispatch — the gap is the full serialized host window: every
      microsecond of streaming/admission/socket work the device waited
      through. (When the harvest returned instantly the device had
      finished somewhere before harvest start, so the measured gap is a
      slight *under*-estimate; it can never over-report idleness.)
    - ``device idle ratio``: windowed ``sum(gaps) / sum(dispatch
      intervals)`` — the fraction of wall time between ticks the device
      was provably idle. The pipelined engine drives this toward 0.

    ``clock`` is injectable (a fake clock makes the accounting exactly
    testable); the optional histogram/gauge mirror the window into the
    registry (``serving_host_gap_seconds`` /
    ``serving_device_idle_ratio``)."""

    def __init__(self, histogram=None, idle_gauge=None,
                 clock=time.monotonic, window: int = 4096):
        self._clock = clock
        self._hist = histogram
        self._gauge = idle_gauge
        self._pending = 0           # dispatched, not yet harvested
        self._last_dispatch: float | None = None
        self._last_harvest_end: float | None = None
        self._harvest_start: float | None = None
        self.last_gap = 0.0
        self.last_harvest_wait = 0.0
        self.gaps = collections.deque(maxlen=window)
        self.intervals = collections.deque(maxlen=window)

    def tick_dispatched(self, t: float | None = None) -> float:
        t = self._clock() if t is None else t
        if self._pending == 0 and self._last_harvest_end is not None:
            gap = max(0.0, t - self._last_harvest_end)
        else:
            # Either the first tick ever, or a tick was still in
            # flight: the device queue was never observed empty.
            gap = 0.0
        self.last_gap = gap
        self.gaps.append(gap)
        if self._hist is not None:
            self._hist.observe(gap)
        if self._last_dispatch is not None:
            self.intervals.append(max(0.0, t - self._last_dispatch))
        self._last_dispatch = t
        self._pending += 1
        return t

    def harvest_started(self, t: float | None = None) -> float:
        self._harvest_start = self._clock() if t is None else t
        return self._harvest_start

    def harvest_ended(self, t: float | None = None) -> float:
        t = self._clock() if t is None else t
        self._pending = max(0, self._pending - 1)
        self._last_harvest_end = t
        self.last_harvest_wait = (max(0.0, t - self._harvest_start)
                                  if self._harvest_start is not None
                                  else 0.0)
        self._harvest_start = None
        if self._gauge is not None:
            self._gauge.set(self.idle_ratio or 0.0)
        return t

    @property
    def idle_ratio(self) -> float | None:
        """Windowed device-idle fraction; None until two ticks ran."""
        total = sum(self.intervals)
        if total <= 0:
            return None
        # gaps has one more entry than intervals (the first dispatch
        # has no interval); drop the first gap for a matched window.
        gaps = list(self.gaps)[-len(self.intervals):]
        return min(1.0, sum(gaps) / total)

    @property
    def gap_p50(self) -> float | None:
        return percentile(self.gaps, 50) if self.gaps else None

    def summary(self) -> dict[str, float]:
        out: dict[str, float] = {}
        if self.gaps:
            out["host_gap_p50_s"] = percentile(self.gaps, 50)
            out["host_gap_p99_s"] = percentile(self.gaps, 99)
            out["host_gap_mean_s"] = sum(self.gaps) / len(self.gaps)
        ratio = self.idle_ratio
        if ratio is not None:
            out["device_idle_ratio"] = ratio
        return out


class BubbleTracker:
    """Pipeline-stage idle ("bubble") accounting — the ``HostGapTracker``
    family's pp sibling, built from the SAME two host instants every
    tick already stamps (dispatch, harvest end).

    A decode tick on a ``pp=S`` mesh occupies each stage for roughly
    ``span / S`` of its dispatch→harvest span (the micro-batch flows
    through the S stage programs back to back). Over a window of ticks,
    total per-stage busy time is ``sum(spans) / S`` while per-stage
    capacity is the window's wall span — so

        ``bubble_fraction = 1 - (sum(spans) / S) / wall``

    is the fraction of stage-time the pipeline provably wasted. One
    tick in flight (``pipeline_depth<=1``) serializes the spans: wall ≈
    sum(spans) and the bubble sits near ``1 - 1/S`` (half the machine
    idle at S=2). ``pipeline_depth>=S`` overlaps micro-batches until
    wall ≈ sum(spans)/S and the bubble approaches 0 — exactly the
    ramp-up/drain-only residue the depth sweep in ``serving_bench
    --pp-ab`` measures. Host-side stalls inside a span bias the
    estimate toward LESS reported idleness, never more (same
    conservative direction as the host-gap tracker), and the fraction
    is clamped to [0, 1]."""

    def __init__(self, gauge=None, window: int = 4096):
        self._gauge = gauge
        self._spans = collections.deque(maxlen=window)
        self.num_stages = 1

    def record(self, t_dispatch: float, t_harvest: float,
               num_stages: int) -> None:
        """One completed tick's dispatch→harvest span."""
        self.num_stages = max(1, int(num_stages))
        self._spans.append((float(t_dispatch), float(t_harvest)))
        if self._gauge is not None:
            f = self.fraction
            if f is not None:
                self._gauge.set(f)

    def reset(self) -> None:
        self._spans.clear()

    @property
    def fraction(self) -> float | None:
        """Windowed stage-idle fraction; None until two ticks ran."""
        if len(self._spans) < 2:
            return None
        wall = (max(t1 for _, t1 in self._spans)
                - min(t0 for t0, _ in self._spans))
        if wall <= 0:
            return None
        busy = sum(t1 - t0 for t0, t1 in self._spans) / self.num_stages
        return min(1.0, max(0.0, 1.0 - busy / wall))

    def summary(self) -> dict[str, float]:
        f = self.fraction
        return {} if f is None else {"bubble_fraction": f}


class ServingMetrics:
    """Accumulates per-request and per-iteration serving metrics.

    ``stream``: optional :class:`MetricStream`; every :meth:`sample` call
    (one per engine decode iteration) emits a structured record, so a
    JSONL sink yields a time series of queue depth / occupancy /
    cumulative token counts alongside the trainers' step records.

    ``registry``: optional :class:`MetricsRegistry` to publish into; a
    private one is created when omitted (tests and multi-engine
    processes stay isolated; pass a shared registry to aggregate).

    Sample series are bounded sliding windows (``window`` most-recent
    entries) — the engine runs for the server's lifetime, and unbounded
    per-token lists would grow to hundreds of MB over a multi-day run.
    Counters (completed/rejected/tokens_out) are exact and unbounded;
    :meth:`summary` percentiles cover the window (the registry histograms
    cover the full lifetime, O(buckets) memory).
    """

    def __init__(self, stream: MetricStream | None = None,
                 window: int = 16384,
                 registry: MetricsRegistry | None = None):
        self.stream = stream
        self.registry = registry if registry is not None else MetricsRegistry()
        self.ttft = collections.deque(maxlen=window)
        self.inter_token = collections.deque(maxlen=window)
        self.queue_wait = collections.deque(maxlen=window)
        self.prefill_device = collections.deque(maxlen=window)
        self.prefill_chunks = collections.deque(maxlen=window)
        self.request_latency = collections.deque(maxlen=window)
        self._occupancy = collections.deque(maxlen=window)
        self._queue_depth = collections.deque(maxlen=window)
        self._iterations = 0
        self._t0 = time.monotonic()

        reg = self.registry
        self._c_completed = reg.counter(
            "serving_requests_completed_total", help="requests completed")
        self._c_rejected = reg.counter(
            "serving_requests_rejected_total", help="backpressure rejects")
        self._c_expired = reg.counter(
            "serving_requests_expired_total", help="deadline expiries")
        self._c_tokens = reg.counter(
            "serving_tokens_out_total", help="tokens streamed to clients")
        self._c_iterations = reg.counter(
            "serving_decode_iterations_total", help="decode loop iterations")
        self._h = {
            "ttft": reg.histogram(
                "serving_ttft_seconds", help="time to first token",
                buckets=_LATENCY_BUCKETS),
            "inter_token": reg.histogram(
                "serving_inter_token_seconds", help="inter-token latency",
                buckets=_LATENCY_BUCKETS),
            "queue_wait": reg.histogram(
                "serving_queue_wait_seconds",
                help="admission wait: submit to slot grant "
                     "(the queueing half of TTFT)",
                buckets=_LATENCY_BUCKETS),
            "prefill_device": reg.histogram(
                "serving_prefill_device_seconds",
                help="prefill device time per request, summed over chunks "
                     "(the compute half of TTFT)",
                buckets=_LATENCY_BUCKETS),
            "prefill_chunks": reg.histogram(
                "serving_prefill_chunks",
                help="prefill chunks per admission",
                buckets=(1, 2, 4, 8, 16, 32, 64)),
            "request_latency": reg.histogram(
                "serving_request_latency_seconds",
                help="submit-to-done latency", buckets=_LATENCY_BUCKETS),
        }
        # Decode-pipeline accounting: the per-tick host gap (device
        # provably idle before a dispatch) and the windowed device-idle
        # fraction — what the overlapped pipeline exists to drive to 0.
        self.host_gap = HostGapTracker(
            histogram=reg.histogram(
                "serving_host_gap_seconds",
                help="host-side gap the device sat idle before a decode "
                     "tick dispatch (pipeline_depth=0 pays this every "
                     "tick; depth 1 hides it behind the in-flight tick)",
                buckets=_LATENCY_BUCKETS),
            idle_gauge=reg.gauge(
                "serving_device_idle_ratio",
                help="windowed fraction of inter-tick wall time the "
                     "device was provably idle (host gap / dispatch "
                     "interval)"))
        # Pipeline-parallel stage-idle accounting: the fraction of
        # stage-time provably wasted to pipeline bubbles (1 - 1/pp at
        # depth<=1; driven toward 0 by depth>=pp micro-batch overlap).
        self.bubble = BubbleTracker(
            gauge=reg.gauge(
                "serving_bubble_fraction",
                help="windowed fraction of pipeline-stage time idle "
                     "between micro-batch ticks (pp meshes; lower is "
                     "better, 0 = every stage busy)"))
        # Speculative decoding: proposed vs committed draft tokens (the
        # ratio is the accept rate — THE health signal for a draft
        # model: it falling means the draft stopped predicting the
        # target and speculation is burning draft compute for nothing),
        # plus the per-row-per-tick accept-length histogram whose
        # exemplars name the request behind an accept-rate collapse.
        self._c_spec_draft = reg.counter(
            "spec_draft_tokens_total",
            help="draft tokens proposed by the speculative decoder")
        self._c_spec_accepted = reg.counter(
            "spec_accepted_tokens_total",
            help="draft tokens accepted (committed) by the target "
                 "verify step")
        self._h["spec_accept_len"] = reg.histogram(
            "serving_spec_accept_len",
            help="accepted drafts per speculating row per tick "
                 "(0..spec_k)",
            buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16))
        self._c_slo_violations = reg.counter(
            "serving_slo_violations_total",
            help="requests that finished slower than the configured "
                 "latency SLO")
        # Paged-KV pool pressure (the pool itself publishes the
        # kv_pool_blocks_* occupancy gauges; these count the engine's
        # RESPONSES to pressure).
        self._c_preemptions = reg.counter(
            "kv_preemptions_total",
            help="decode slots evicted (blocks released, request "
                 "requeued) because the KV block pool ran dry")
        self._c_oom_rejections = reg.counter(
            "kv_oom_rejections_total",
            help="requests rejected because their full context can "
                 "never fit the KV block pool")
        # KV block migration (disaggregated prefill/decode + cross-
        # replica prefix sharing, serving/kv_transfer.py): adoptions of
        # a peer's blocks, bytes moved, fallbacks to monolithic
        # prefill, and the pull latency with trace exemplars — the
        # family the "decode fleet starving" runbook triages first.
        self._c_kv_migrations = reg.counter(
            "kv_migrations_total",
            help="KV block chains adopted from a peer replica "
                 "(disaggregated handoff / cross-replica prefix share "
                 "/ slot migration)")
        self._c_kv_migration_fallbacks = reg.counter(
            "kv_migration_fallbacks_total",
            help="KV migrations that fell back to monolithic prefill "
                 "(peer unreachable/miss, provenance mismatch, pool "
                 "dry) — never a client-visible error")
        self._c_kv_migration_bytes = reg.counter(
            "kv_migration_bytes_total",
            help="serialized KV block bytes adopted from peers")
        self._c_kv_exports = reg.counter(
            "kv_exports_total",
            help="KV block chains serialized and shipped to a peer")
        self._h["kv_migration"] = reg.histogram(
            "kv_migration_seconds",
            help="peer pull + adopt latency per KV migration",
            buckets=_LATENCY_BUCKETS)
        # Tiered KV cache (serving/kv_tier.py): device⇄host block
        # movement plus router-scheduled P→D pushes. The tier itself
        # publishes kv_tier_{host,disk}_bytes occupancy; these count
        # the ENGINE's traffic through it, exemplar'd with the trace
        # that triggered each move.
        self._c_kv_spills = reg.counter(
            "kv_tier_spills_total",
            help="trie eviction victims spilled D2H into the host tier")
        self._c_kv_spill_bytes = reg.counter(
            "kv_tier_spill_bytes_total",
            help="serialized KVX1 bytes spilled into the host tier")
        self._c_kv_readmits = reg.counter(
            "kv_tier_readmits_total",
            help="blocks re-admitted H2D from the host tier on a trie "
                 "miss during admission")
        self._c_kv_readmit_bytes = reg.counter(
            "kv_tier_readmit_bytes_total",
            help="serialized KVX1 bytes re-admitted from the host tier")
        self._c_kv_pushes = reg.counter(
            "kv_pushes_total",
            help="KV chains pushed to a peer (router-scheduled P→D "
                 "transfer, replacing an adopt-time pull)")
        self._c_kv_push_bytes = reg.counter(
            "kv_push_bytes_total",
            help="serialized KV bytes delivered by push transfers")
        self._c_kv_push_fallbacks = reg.counter(
            "kv_push_fallbacks_total",
            help="push transfers that failed (receiver pulls or "
                 "re-prefills instead) — never a client-visible error")
        self._h["kv_spill"] = reg.histogram(
            "kv_tier_spill_seconds",
            help="D2H gather + serialize latency per spilled block",
            buckets=_LATENCY_BUCKETS)
        self._h["kv_readmit"] = reg.histogram(
            "kv_tier_readmit_seconds",
            help="host-tier probe + H2D scatter latency per "
                 "re-admission burst",
            buckets=_LATENCY_BUCKETS)
        self._h["kv_push"] = reg.histogram(
            "kv_push_seconds",
            help="export + deliver + remote-adopt latency per push",
            buckets=_LATENCY_BUCKETS)
        self._g_kv_tier_resident = reg.gauge(
            "kv_tier_resident_bytes",
            help="bytes resident in the DEVICE pool tier (blocks_used "
                 "x bytes_per_block)")
        self._g_slo = reg.gauge(
            "serving_slo_seconds",
            help="configured request-latency SLO (0 = no SLO armed)")
        self._c_prefix_hit_tokens = reg.counter(
            "serving_prefix_hit_tokens_total",
            help="admitted prompt tokens served from the prefix cache")
        self._c_prompt_tokens = reg.counter(
            "serving_prompt_tokens_total",
            help="admitted prompt tokens total")
        # Weight provenance: numeric version gauge plus an info-style
        # gauge whose LABELS carry the digest (the Prometheus idiom for
        # string facts); superseded info series drop to 0 so a scrape
        # shows exactly one live (version, digest) at value 1.
        self._g_weight_version = reg.gauge(
            "serving_weight_version",
            help="monotonic version of the live weights (0 = unversioned "
                 "init)")
        self._last_weight_info: object | None = None
        self._prev_weight_info: object | None = None
        self._g_queue_depth = reg.gauge(
            "serving_queue_depth", help="queued requests")
        self._g_slots_active = reg.gauge(
            "serving_slots_active", help="occupied decode slots")
        self._g_occupancy = reg.gauge(
            "serving_slot_occupancy", help="occupied / total slots")
        # Multi-tenant accounting: lifetime completed/token counters and
        # the per-tenant occupancy gauge, all labeled by tenant through
        # ONE shared cardinality-capping labeler (the engine hands the
        # same instance to its Scheduler, so a tenant is labeled — or
        # folded into "__other__" — consistently across every family).
        from distkeras_tpu.serving.scheduler import TenantLabeler

        self.tenant_labeler = TenantLabeler()
        self._tenant_completed: dict[str, int] = {}
        self._tenant_tokens: dict[str, int] = {}
        self._tenant_active_gauges: dict[str, object] = {}
        # Request kinds (generate / sample / score / embed): per-kind
        # admission counter (bounded label set — the kind vocabulary is
        # fixed), CoW fork block-share counter, and the mask-upload
        # latency of constrained decoding (the host-side cost of every
        # automaton state change; a regression here shows up as
        # inter-token jitter on constrained streams).
        self._kind_counters: dict[str, object] = {}
        self._c_fork_blocks = reg.counter(
            "kv_fork_blocks_total",
            help="extra copy-on-write shares handed out on KV blocks by "
                 "forked sampling (one per block per extra fork row)")
        self._h["mask_upload"] = reg.histogram(
            "mask_upload_seconds",
            help="host→device upload latency of the constrained-decoding "
                 "token mask (per dirty-mask decode dispatch)",
            buckets=_LATENCY_BUCKETS)

    # -- counter compatibility surface (pre-registry attribute names) -------
    @property
    def completed(self) -> int:
        return int(self._c_completed.value)

    @property
    def rejected(self) -> int:
        return int(self._c_rejected.value)

    @property
    def expired(self) -> int:
        return int(self._c_expired.value)

    @property
    def tokens_out(self) -> int:
        return int(self._c_tokens.value)

    # -- per-request events -------------------------------------------------
    def record_admit(self, queue_wait_s: float) -> None:
        """Admission wait: submit to slot grant (TTFT's queueing half)."""
        self.queue_wait.append(queue_wait_s)
        self._h["queue_wait"].observe(queue_wait_s)

    def record_prefill(self, device_s: float, chunks: int,
                       matched_tokens: int | None,
                       prompt_tokens: int) -> None:
        """Prefill completed: device seconds summed over its chunks
        (TTFT's compute half), chunk count, and how much of the prompt
        the prefix cache served (``matched_tokens`` of
        ``prompt_tokens``). ``matched_tokens=None`` means no prefix
        cache is configured — the hit counters stay untouched so
        summaries don't report a 0.0 hit rate for a cache that does not
        exist."""
        self.prefill_device.append(device_s)
        self._h["prefill_device"].observe(device_s)
        self.prefill_chunks.append(chunks)
        self._h["prefill_chunks"].observe(chunks)
        if matched_tokens is not None:
            self._c_prefix_hit_tokens.inc(matched_tokens)
            self._c_prompt_tokens.inc(prompt_tokens)

    def record_first_token(self, ttft_s: float,
                           trace_id: str | None = None) -> None:
        """``trace_id`` becomes the histogram's per-bucket worst-sample
        exemplar: a TTFT p99 spike on the scrape page names the request
        whose flight-recorder timeline explains it."""
        self.ttft.append(ttft_s)
        self._h["ttft"].observe(ttft_s, exemplar=trace_id)
        self._c_tokens.inc()

    def record_inter_token(self, gap_s: float,
                           trace_id: str | None = None) -> None:
        self.inter_token.append(gap_s)
        self._h["inter_token"].observe(gap_s, exemplar=trace_id)
        self._c_tokens.inc()

    def record_finish(self, latency_s: float) -> None:
        self._c_completed.inc()
        self.request_latency.append(latency_s)
        self._h["request_latency"].observe(latency_s)

    def record_reject(self) -> None:
        self._c_rejected.inc()

    def record_expire(self) -> None:
        self._c_expired.inc()

    def set_slo(self, slo_s: float) -> None:
        self._g_slo.set(slo_s)

    def set_weight_version(self, provenance: dict | None) -> None:
        """Publish the live weights' provenance: ``serving_weight_version``
        (numeric) and ``serving_weight_info{version=,digest=} 1`` (the
        digest as a label). The immediately-superseded info series is
        zeroed (the transition is visible on the next scrape); anything
        older is unregistered — a replica on a continuous reload
        cadence must not grow its scrape with one dead series per
        reload."""
        if not provenance:
            return
        version = int(provenance.get("version") or 0)
        self._g_weight_version.set(version)
        info = self.registry.gauge(
            "serving_weight_info",
            help="1 for the live weights' (version, digest); the "
                 "just-superseded series reads 0, older ones are dropped",
            version=str(version),
            digest=str(provenance.get("digest")))
        if self._last_weight_info is not None \
                and self._last_weight_info is not info:
            if self._prev_weight_info is not None \
                    and self._prev_weight_info is not info:
                self.registry.remove(self._prev_weight_info)
            self._last_weight_info.set(0)
            self._prev_weight_info = self._last_weight_info
        info.set(1)
        self._last_weight_info = info

    def record_spec(self, drafted: int, accepted: int,
                    trace_id: str | None = None) -> None:
        """One speculating row's tick: ``drafted`` tokens proposed that
        the row could actually use (spec_k, clamped by its remaining
        budget), ``accepted`` of them committed. ``trace_id`` pins the
        bucket's worst-sample exemplar so an accept-len p~0 bucket
        names a request whose stream the draft model cannot predict."""
        self._c_spec_draft.inc(drafted)
        self._c_spec_accepted.inc(accepted)
        self._h["spec_accept_len"].observe(accepted, exemplar=trace_id)

    @property
    def spec_draft_tokens(self) -> int:
        return int(self._c_spec_draft.value)

    @property
    def spec_accepted_tokens(self) -> int:
        return int(self._c_spec_accepted.value)

    def _tenant_label(self, tenant: str) -> str:
        return self.tenant_labeler(tenant)

    def record_tenant_done(self, tenant: str, tokens: int) -> None:
        """One completed request's per-tenant accounting: request and
        token counters, both as host dicts (healthz rollups) and labeled
        registry counters (metricsz)."""
        label = self._tenant_label(tenant)
        self._tenant_completed[label] = (
            self._tenant_completed.get(label, 0) + 1)
        self._tenant_tokens[label] = (
            self._tenant_tokens.get(label, 0) + int(tokens))
        self.registry.counter(
            "serving_tenant_requests_completed_total",
            help="completed requests per tenant", tenant=label).inc()
        self.registry.counter(
            "serving_tenant_tokens_out_total",
            help="tokens streamed per tenant", tenant=label).inc(
                int(tokens))

    # -- request kinds ------------------------------------------------------
    def record_request_kind(self, kind: str) -> None:
        """One admitted request of ``kind`` — the per-kind traffic
        counter ``serving_requests_total{kind=}``. The label set is the
        fixed kind vocabulary, so cardinality is bounded by
        construction (no labeler needed)."""
        c = self._kind_counters.get(kind)
        if c is None:
            c = self.registry.counter(
                "serving_requests_total",
                help="admitted requests per request kind",
                kind=str(kind))
            self._kind_counters[kind] = c
        c.inc()

    def kind_counters(self) -> dict[str, int]:
        return {k: int(c.value) for k, c in self._kind_counters.items()}

    def record_fork_blocks(self, n: int) -> None:
        """``n`` extra copy-on-write block shares handed out at a fork
        (blocks × (n_forks - 1)) — the block-sharing ratio's numerator
        in serving_bench's fork rows."""
        self._c_fork_blocks.inc(int(n))

    @property
    def fork_blocks(self) -> int:
        return int(self._c_fork_blocks.value)

    def record_mask_upload(self, seconds: float,
                           trace_id: str | None = None) -> None:
        """One dirty-mask host→device upload before a constrained decode
        dispatch; the exemplar names the constrained stream that paid a
        slow upload."""
        self._h["mask_upload"].observe(seconds, exemplar=trace_id)

    def tenant_counters(self) -> dict[str, dict]:
        return {t: {"completed": self._tenant_completed.get(t, 0),
                    "tokens_out": self._tenant_tokens.get(t, 0)}
                for t in self._tenant_completed}

    def set_tenant_active(self, active: dict[str, int]) -> None:
        """Refresh the per-tenant occupancy gauges; tenants that dropped
        to zero active slots read 0 (their series stays, bounded by the
        label cap) so a scrape sees the release, not a stale high.
        Counts aggregate per LABEL (over-cap tenants share
        ``__other__``) so the folded series reports the sum, not one
        arbitrary tenant's value."""
        by_label: dict[str, int] = {}
        for tenant, n in active.items():
            label = self._tenant_label(tenant)
            by_label[label] = by_label.get(label, 0) + int(n)
        for label, gauge in self._tenant_active_gauges.items():
            if label not in by_label:
                gauge.set(0)
        for label, n in by_label.items():
            g = self._tenant_active_gauges.get(label)
            if g is None:
                g = self.registry.gauge(
                    "serving_tenant_slots_active",
                    help="occupied decode slots per tenant",
                    tenant=label)
                self._tenant_active_gauges[label] = g
            g.set(n)

    def record_slo_violation(self) -> None:
        self._c_slo_violations.inc()

    @property
    def slo_violations(self) -> int:
        return int(self._c_slo_violations.value)

    def record_preemption(self) -> None:
        """A decode slot was evicted for KV blocks and its request
        requeued (paged oversubscription doing its job — frequent
        preemption means the pool is undersized for the offered load)."""
        self._c_preemptions.inc()

    def record_oom_reject(self) -> None:
        self._c_oom_rejections.inc()

    def record_kv_migration(self, nbytes: int, latency_s: float,
                            trace_id: str | None = None) -> None:
        """One adopted KV block migration: bytes moved + pull-to-adopt
        latency, exemplar'd with the request it served."""
        self._c_kv_migrations.inc()
        self._c_kv_migration_bytes.inc(int(nbytes))
        self._h["kv_migration"].observe(latency_s, exemplar=trace_id)

    def record_kv_migration_fallback(self) -> None:
        self._c_kv_migration_fallbacks.inc()

    def record_kv_export(self, nbytes: int) -> None:
        self._c_kv_exports.inc()

    def record_kv_spill(self, nbytes: int, latency_s: float,
                        trace_id: str | None = None) -> None:
        """One trie eviction victim spilled into the host tier."""
        self._c_kv_spills.inc()
        self._c_kv_spill_bytes.inc(int(nbytes))
        self._h["kv_spill"].observe(latency_s, exemplar=trace_id)

    def record_kv_readmit(self, blocks: int, nbytes: int, latency_s: float,
                          trace_id: str | None = None) -> None:
        """One admission-time re-admission burst from the host tier."""
        self._c_kv_readmits.inc(int(blocks))
        self._c_kv_readmit_bytes.inc(int(nbytes))
        self._h["kv_readmit"].observe(latency_s, exemplar=trace_id)

    def record_kv_push(self, nbytes: int, latency_s: float,
                       trace_id: str | None = None) -> None:
        """One KV chain pushed to a peer and adopted there."""
        self._c_kv_pushes.inc()
        self._c_kv_push_bytes.inc(int(nbytes))
        self._h["kv_push"].observe(latency_s, exemplar=trace_id)

    def record_kv_push_fallback(self) -> None:
        self._c_kv_push_fallbacks.inc()

    def set_kv_tier_resident_bytes(self, nbytes: int) -> None:
        self._g_kv_tier_resident.set(int(nbytes))

    @property
    def kv_migrations(self) -> int:
        return int(self._c_kv_migrations.value)

    @property
    def kv_migration_fallbacks(self) -> int:
        return int(self._c_kv_migration_fallbacks.value)

    @property
    def kv_migration_bytes(self) -> int:
        return int(self._c_kv_migration_bytes.value)

    @property
    def kv_exports(self) -> int:
        return int(self._c_kv_exports.value)

    @property
    def kv_spills(self) -> int:
        return int(self._c_kv_spills.value)

    @property
    def kv_spill_bytes(self) -> int:
        return int(self._c_kv_spill_bytes.value)

    @property
    def kv_readmits(self) -> int:
        return int(self._c_kv_readmits.value)

    @property
    def kv_readmit_bytes(self) -> int:
        return int(self._c_kv_readmit_bytes.value)

    @property
    def kv_pushes(self) -> int:
        return int(self._c_kv_pushes.value)

    @property
    def kv_push_bytes(self) -> int:
        return int(self._c_kv_push_bytes.value)

    @property
    def kv_push_fallbacks(self) -> int:
        return int(self._c_kv_push_fallbacks.value)

    @property
    def preemptions(self) -> int:
        return int(self._c_preemptions.value)

    @property
    def oom_rejections(self) -> int:
        return int(self._c_oom_rejections.value)

    @property
    def iterations(self) -> int:
        """Decode-loop iterations sampled so far (per-request timeline
        records diff this around a request's lifetime)."""
        return self._iterations

    # -- per-iteration sampling --------------------------------------------
    def sample(self, queue_depth: int, slots_active: int, slots_total: int) -> None:
        """Call once per decode iteration; emits one stream record."""
        self._iterations += 1
        self._c_iterations.inc()
        occ = slots_active / max(1, slots_total)
        self._occupancy.append(occ)
        self._queue_depth.append(queue_depth)
        self._g_queue_depth.set(queue_depth)
        self._g_slots_active.set(slots_active)
        self._g_occupancy.set(occ)
        if self.stream is not None:
            self.stream.emit(self._iterations, {
                "queue_depth": queue_depth,
                "slots_active": slots_active,
                "slot_occupancy": occ,
                "tokens_out": self.tokens_out,
                "completed": self.completed,
                "rejected": self.rejected,
                "expired": self.expired,
            })

    # -- rollup -------------------------------------------------------------
    def summary(self) -> dict[str, float]:
        """Percentile rollup (``StepTimer.summary`` key conventions)."""
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        out: dict[str, float] = {
            "requests_completed": float(self.completed),
            "requests_rejected": float(self.rejected),
            "requests_expired": float(self.expired),
            "tokens_out": float(self.tokens_out),
            "tokens_per_sec": self.tokens_out / elapsed,
            "elapsed_s": elapsed,
            "decode_iterations": float(self._iterations),
        }
        if self._g_slo.value:
            out["slo_violations"] = float(self.slo_violations)
        if self.preemptions or self.oom_rejections:
            out["kv_preemptions"] = float(self.preemptions)
            out["kv_oom_rejections"] = float(self.oom_rejections)
        for name, xs in (
            ("ttft", self.ttft),
            ("inter_token", self.inter_token),
            ("queue_wait", self.queue_wait),
            ("prefill_device", self.prefill_device),
            ("request_latency", self.request_latency),
        ):
            if xs:
                out[f"{name}_p50_s"] = percentile(xs, 50)
                out[f"{name}_p95_s"] = percentile(xs, 95)
                out[f"{name}_p99_s"] = percentile(xs, 99)
                out[f"{name}_mean_s"] = sum(xs) / len(xs)
        out.update(self.host_gap.summary())
        out.update(self.bubble.summary())
        if self.prefill_chunks:
            out["prefill_chunks_mean"] = (
                sum(self.prefill_chunks) / len(self.prefill_chunks))
            out["prefill_chunks_max"] = float(max(self.prefill_chunks))
        if self._c_prompt_tokens.value:
            out["prefix_hit_rate"] = (
                self._c_prefix_hit_tokens.value / self._c_prompt_tokens.value)
        if self.kv_spills or self.kv_readmits:
            out["kv_spills"] = float(self.kv_spills)
            out["kv_spill_bytes"] = float(self.kv_spill_bytes)
            out["kv_readmits"] = float(self.kv_readmits)
            out["kv_readmit_bytes"] = float(self.kv_readmit_bytes)
            if self._h["kv_spill"].count:
                out["kv_spill_latency_p99_s"] = (
                    self._h["kv_spill"].percentile(99))
            if self._h["kv_readmit"].count:
                out["kv_readmit_latency_p99_s"] = (
                    self._h["kv_readmit"].percentile(99))
        for kind, n in self.kind_counters().items():
            out[f"requests_kind_{kind}"] = float(n)
        if self.fork_blocks:
            out["kv_fork_blocks"] = float(self.fork_blocks)
        if self._h["mask_upload"].count:
            out["mask_upload_count"] = float(self._h["mask_upload"].count)
            out["mask_upload_mean_s"] = float(self._h["mask_upload"].mean)
            out["mask_upload_p99_s"] = self._h["mask_upload"].percentile(99)
        if self._c_spec_draft.value:
            out["spec_draft_tokens"] = float(self.spec_draft_tokens)
            out["spec_accepted_tokens"] = float(self.spec_accepted_tokens)
            out["spec_accept_rate"] = (
                self.spec_accepted_tokens / self.spec_draft_tokens)
        if self._occupancy:
            out["slot_occupancy_mean"] = (
                sum(self._occupancy) / len(self._occupancy)
            )
            out["queue_depth_max"] = float(max(self._queue_depth))
        return out

    def emit_summary(self, step: int = -1) -> dict[str, float]:
        """Emit the rollup through the stream (step -1 marks a summary
        record among the per-iteration series) and return it."""
        s = self.summary()
        if self.stream is not None:
            self.stream.emit(step, {"summary": 1.0, **s})
        return s
