"""Serving observability: latency-under-load metrics.

Training benchmarks in this repo measure *throughput* (samples/sec/chip,
``tracing.StepTimer``); a serving engine is judged on *latency under
load*: TTFT (time to first token — dominated by queueing + prefill),
inter-token latency (decode-step cadence), queue depth, slot occupancy,
and goodput (tokens/sec actually delivered). :class:`ServingMetrics`
accumulates those and emits structured records through the same
:class:`distkeras_tpu.tracing.MetricStream` JSONL sinks the trainers use;
:meth:`ServingMetrics.summary` follows ``StepTimer.summary``'s key
conventions (``*_p50_s`` etc.) with the tail percentiles (p95/p99) that
matter for serving SLOs.
"""

from __future__ import annotations

import collections
import time

from distkeras_tpu.tracing import MetricStream

__all__ = ["ServingMetrics", "percentile"]


def percentile(values, q: float) -> float:
    """Linear-interpolated percentile of ``values`` (any sized iterable
    of floats); ``q`` in [0, 100]."""
    if not values:
        raise ValueError("percentile of empty list")
    import numpy as np

    return float(np.percentile(np.fromiter(values, dtype=np.float64), q))


class ServingMetrics:
    """Accumulates per-request and per-iteration serving metrics.

    ``stream``: optional :class:`MetricStream`; every :meth:`sample` call
    (one per engine decode iteration) emits a structured record, so a
    JSONL sink yields a time series of queue depth / occupancy /
    cumulative token counts alongside the trainers' step records.

    Sample series are bounded sliding windows (``window`` most-recent
    entries) — the engine runs for the server's lifetime, and unbounded
    per-token lists would grow to hundreds of MB over a multi-day run.
    Counters (completed/rejected/tokens_out) are exact and unbounded;
    :meth:`summary` percentiles cover the window.
    """

    def __init__(self, stream: MetricStream | None = None,
                 window: int = 16384):
        self.stream = stream
        self.ttft = collections.deque(maxlen=window)
        self.inter_token = collections.deque(maxlen=window)
        self.queue_wait = collections.deque(maxlen=window)
        self.request_latency = collections.deque(maxlen=window)
        self.completed = 0
        self.rejected = 0
        self.expired = 0
        self.tokens_out = 0
        self._occupancy = collections.deque(maxlen=window)
        self._queue_depth = collections.deque(maxlen=window)
        self._iterations = 0
        self._t0 = time.monotonic()

    # -- per-request events -------------------------------------------------
    def record_admit(self, queue_wait_s: float) -> None:
        self.queue_wait.append(queue_wait_s)

    def record_first_token(self, ttft_s: float) -> None:
        self.ttft.append(ttft_s)
        self.tokens_out += 1

    def record_inter_token(self, gap_s: float) -> None:
        self.inter_token.append(gap_s)
        self.tokens_out += 1

    def record_finish(self, latency_s: float) -> None:
        self.completed += 1
        self.request_latency.append(latency_s)

    def record_reject(self) -> None:
        self.rejected += 1

    def record_expire(self) -> None:
        self.expired += 1

    # -- per-iteration sampling --------------------------------------------
    def sample(self, queue_depth: int, slots_active: int, slots_total: int) -> None:
        """Call once per decode iteration; emits one stream record."""
        self._iterations += 1
        occ = slots_active / max(1, slots_total)
        self._occupancy.append(occ)
        self._queue_depth.append(queue_depth)
        if self.stream is not None:
            self.stream.emit(self._iterations, {
                "queue_depth": queue_depth,
                "slots_active": slots_active,
                "slot_occupancy": occ,
                "tokens_out": self.tokens_out,
                "completed": self.completed,
                "rejected": self.rejected,
                "expired": self.expired,
            })

    # -- rollup -------------------------------------------------------------
    def summary(self) -> dict[str, float]:
        """Percentile rollup (``StepTimer.summary`` key conventions)."""
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        out: dict[str, float] = {
            "requests_completed": float(self.completed),
            "requests_rejected": float(self.rejected),
            "requests_expired": float(self.expired),
            "tokens_out": float(self.tokens_out),
            "tokens_per_sec": self.tokens_out / elapsed,
            "elapsed_s": elapsed,
            "decode_iterations": float(self._iterations),
        }
        for name, xs in (
            ("ttft", self.ttft),
            ("inter_token", self.inter_token),
            ("queue_wait", self.queue_wait),
            ("request_latency", self.request_latency),
        ):
            if xs:
                out[f"{name}_p50_s"] = percentile(xs, 50)
                out[f"{name}_p95_s"] = percentile(xs, 95)
                out[f"{name}_p99_s"] = percentile(xs, 99)
                out[f"{name}_mean_s"] = sum(xs) / len(xs)
        if self._occupancy:
            out["slot_occupancy_mean"] = (
                sum(self._occupancy) / len(self._occupancy)
            )
            out["queue_depth_max"] = float(max(self._queue_depth))
        return out

    def emit_summary(self, step: int = -1) -> dict[str, float]:
        """Emit the rollup through the stream (step -1 marks a summary
        record among the per-iteration series) and return it."""
        s = self.summary()
        if self.stream is not None:
            self.stream.emit(step, {"summary": 1.0, **s})
        return s
