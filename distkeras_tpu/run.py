"""CLI runner: ``python -m distkeras_tpu.run --config job.json --data d.npz``.

The executable form of a ``TrainerConfig`` — what a ``Job``/``Punchcard``
ships to a TPU host. The config JSON carries the trainer spec (see
:mod:`distkeras_tpu.utils.config`); data arrives as an ``.npz`` with
``features``/``label`` arrays or a headered CSV; the model comes from the
built-in zoo by name.

Example config:
    {"trainer": "ADAG", "worker_optimizer": "adam", "learning_rate": 1e-3,
     "num_workers": 4, "batch_size": 64, "num_epoch": 2,
     "communication_window": 12}
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

MODEL_ZOO = {
    "mnist_mlp": ("distkeras_tpu.models.mlp", "mnist_mlp"),
    "higgs_mlp": ("distkeras_tpu.models.mlp", "higgs_mlp"),
    "mnist_cnn": ("distkeras_tpu.models.cnn", "mnist_cnn"),
    "cifar10_cnn": ("distkeras_tpu.models.cnn", "cifar10_cnn"),
    "resnet18": ("distkeras_tpu.models.resnet", "resnet18"),
    "resnet50": ("distkeras_tpu.models.resnet", "resnet50"),
    "bert_tiny_mlm": ("distkeras_tpu.models.bert", "bert_tiny_mlm"),
    "bert_base_mlm": ("distkeras_tpu.models.bert", "bert_base_mlm"),
}


def load_model(name: str, kwargs: dict):
    import importlib

    if name not in MODEL_ZOO:
        raise SystemExit(f"unknown model {name!r}; known: {sorted(MODEL_ZOO)}")
    mod, fn = MODEL_ZOO[name]
    return getattr(importlib.import_module(mod), fn)(**kwargs)


def load_data(path: str, features_col: str, label_col: str):
    from distkeras_tpu.data.dataset import Dataset

    if path.endswith(".npz"):
        with np.load(path) as d:
            return Dataset.from_arrays(
                **{features_col: d["features"], label_col: d["label"]}
            )
    header = open(path).readline().strip().split(",")
    return Dataset.from_csv(
        path, features=[c for c in header if c != label_col], label=label_col,
        features_col=features_col, label_col=label_col,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="distkeras_tpu.run")
    ap.add_argument("--config", required=True, help="TrainerConfig JSON file")
    ap.add_argument("--data", required=True, help=".npz (features/label) or CSV")
    ap.add_argument("--model", default="mnist_mlp", help=f"one of {sorted(MODEL_ZOO)}")
    ap.add_argument("--model-args", default="{}", help="JSON kwargs for the model fn")
    ap.add_argument("--out", default=None, help="path to save trained weights")
    ap.add_argument("--metrics-out", default=None, help="JSONL per-step metrics")
    ap.add_argument("--shuffle", action="store_true")
    args = ap.parse_args(argv)

    from distkeras_tpu.tracing import MetricStream
    from distkeras_tpu.utils.config import TrainerConfig

    cfg = TrainerConfig.from_json(open(args.config).read())
    model = load_model(args.model, json.loads(args.model_args))
    ds = load_data(args.data, cfg.features_col, cfg.label_col)
    trainer = cfg.build(model)
    if args.metrics_out:
        trainer.metric_stream = MetricStream.to_jsonl(args.metrics_out)

    trained = trainer.train(ds, shuffle=args.shuffle)
    summary = {
        "trainer": cfg.trainer,
        "steps": len(trainer.get_history()),
        "training_time_s": round(trainer.get_training_time(), 3),
        "averaged_history": {
            k: round(v, 5) for k, v in trainer.get_averaged_history().items()
        },
    }
    if args.out:
        if isinstance(trained, list):  # EnsembleTrainer
            for i, t in enumerate(trained):
                t.save_weights(f"{args.out}.{i}")
            summary["saved"] = [f"{args.out}.{i}" for i in range(len(trained))]
        else:
            trained.save_weights(args.out)
            summary["saved"] = args.out
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
