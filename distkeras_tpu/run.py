"""CLI runner: ``python -m distkeras_tpu.run --config job.json --data d.npz``.

The executable form of a ``TrainerConfig`` — what a ``Job``/``Punchcard``
ships to a TPU host. The config JSON carries the trainer spec (see
:mod:`distkeras_tpu.utils.config`); data arrives as an ``.npz`` with
``features``/``label`` arrays or a headered CSV; the model comes from the
built-in zoo by name.

Example config:
    {"trainer": "ADAG", "worker_optimizer": "adam", "learning_rate": 1e-3,
     "num_workers": 4, "batch_size": 64, "num_epoch": 2,
     "communication_window": 12}

Online serving (``python -m distkeras_tpu.run serve --model gpt_tiny
--port 8500``) starts the continuous-batching TCP server
(:mod:`distkeras_tpu.serving`) over a causal LM from the zoo;
``serve --replicas N`` (or the ``cluster`` subcommand) starts N replica
processes behind a supervised router with automatic restarts and
zero-downtime rolling weight reloads
(:mod:`distkeras_tpu.serving.cluster`).
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

MODEL_ZOO = {
    "mnist_mlp": ("distkeras_tpu.models.mlp", "mnist_mlp"),
    "higgs_mlp": ("distkeras_tpu.models.mlp", "higgs_mlp"),
    "mnist_cnn": ("distkeras_tpu.models.cnn", "mnist_cnn"),
    "cifar10_cnn": ("distkeras_tpu.models.cnn", "cifar10_cnn"),
    "resnet18": ("distkeras_tpu.models.resnet", "resnet18"),
    "resnet50": ("distkeras_tpu.models.resnet", "resnet50"),
    "bert_tiny_mlm": ("distkeras_tpu.models.bert", "bert_tiny_mlm"),
    "bert_base_mlm": ("distkeras_tpu.models.bert", "bert_base_mlm"),
    "gpt_tiny": ("distkeras_tpu.models.bert", "gpt_tiny"),
    "gpt_small": ("distkeras_tpu.models.bert", "gpt_small"),
}


def load_model(name: str, kwargs: dict):
    import importlib

    if name not in MODEL_ZOO:
        raise SystemExit(f"unknown model {name!r}; known: {sorted(MODEL_ZOO)}")
    mod, fn = MODEL_ZOO[name]
    return getattr(importlib.import_module(mod), fn)(**kwargs)


def load_data(path: str, features_col: str, label_col: str):
    from distkeras_tpu.data.dataset import Dataset

    if path.endswith(".npz"):
        with np.load(path) as d:
            return Dataset.from_arrays(
                **{features_col: d["features"], label_col: d["label"]}
            )
    header = open(path).readline().strip().split(",")
    return Dataset.from_csv(
        path, features=[c for c in header if c != label_col], label=label_col,
        features_col=features_col, label_col=label_col,
    )


def serve_main(argv=None, prog="serve", default_replicas=1) -> int:
    """``serve`` subcommand: continuous-batching TCP server over a causal
    LM from the zoo (random-init demo weights unless --weights given).
    ``--replicas N`` (or the ``cluster`` subcommand) instead starts N
    replica processes behind a supervised router on ``--port``."""
    ap = argparse.ArgumentParser(prog=f"distkeras_tpu.run {prog}")
    ap.add_argument("--model", default="gpt_tiny",
                    help="causal LM from the zoo (gpt_tiny/gpt_small)")
    ap.add_argument("--model-args", default="{}",
                    help="JSON kwargs for the model fn")
    ap.add_argument("--weights", default=None,
                    help="serialized-pytree weights (save_weights output); "
                         "random init when omitted")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8500, help="0 = ephemeral")
    ap.add_argument("--slots", type=int, default=4,
                    help="decode batch width (concurrent requests)")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="admission queue depth before queue_full rejects")
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="split prompt prefill into chunks of this many "
                         "tokens, one per decode tick — bounds the decode "
                         "stall (p99 ITL) a long prompt can cause; "
                         "default: monolithic prefill")
    ap.add_argument("--prefix-cache-mb", type=float, default=0.0,
                    help="> 0 enables the device-resident prompt prefix "
                         "cache under this byte budget: shared prefixes "
                         "(system prompts, templates) splice cached KV "
                         "blocks instead of recomputing prefill")
    ap.add_argument("--prefix-block", type=int, default=16,
                    help="prefix-cache block granularity in tokens")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=default_replicas,
                    help="> 1: start this many replica processes behind a "
                         "supervised router on --port (least-outstanding "
                         "routing with prefix-cache affinity, automatic "
                         "restarts, rolling weight reloads)")
    ap.add_argument("--affinity-slack", type=int, default=4,
                    help="cluster mode: max outstanding-request imbalance "
                         "the prefix-affinity pin may create before plain "
                         "least-outstanding routing wins")
    ap.add_argument("--replica-env", action="append", default=[],
                    metavar="KEY=VAL",
                    help="cluster mode, repeatable: extra env var for each "
                         "replica child; '{i}' expands to the replica "
                         "index — the device-partitioning hook (e.g. "
                         "CUDA_VISIBLE_DEVICES={i} so N replicas on one "
                         "accelerator host each claim one chip instead of "
                         "all of them)")
    ap.add_argument("--metrics-out", default=None,
                    help="JSONL per-iteration serving metrics")
    ap.add_argument("--trace-out", default=None,
                    help="enable spans; write Chrome-trace JSON (Perfetto-"
                         "loadable) here on shutdown")
    ap.add_argument("--audit-recompiles", nargs="?", const="report",
                    choices=["report", "arm"], default=None,
                    help="count compiles per jitted program (report at "
                         "exit); 'arm' additionally fails loudly if the "
                         "decode step ever recompiles after its first "
                         "iteration")
    args = ap.parse_args(argv)
    if args.replicas > 1:
        return cluster_main(args)

    import asyncio

    from distkeras_tpu.serving import (
        ServingEngine, ServingMetrics, ServingServer,
    )
    from distkeras_tpu.telemetry import RecompileAuditor, enable_tracing
    from distkeras_tpu.tracing import MetricStream

    from distkeras_tpu.telemetry import MetricsRegistry

    tracer = enable_tracing() if args.trace_out else None
    model = load_model(args.model, json.loads(args.model_args))
    variables = model.init(args.seed)
    if args.weights:
        from distkeras_tpu.checkpoint import load_weights_file

        variables = load_weights_file(args.weights, like=variables)
    # One registry behind everything this process publishes — serving
    # metrics, the scheduler, the stream's last-value gauges, the auditor
    # — so a metricsz scrape shows the whole picture.
    registry = MetricsRegistry()
    metrics = ServingMetrics(
        MetricStream.to_jsonl(args.metrics_out, registry=registry)
        if args.metrics_out else None,
        registry=registry)
    auditor = (RecompileAuditor(registry=registry)
               if args.audit_recompiles else None)
    engine = ServingEngine(
        model, variables, slots=args.slots, max_queue=args.max_queue,
        top_k=args.top_k, metrics=metrics, seed=args.seed,
        auditor=auditor,
        arm_auditor_after_warmup=args.audit_recompiles == "arm",
        prefill_chunk=args.prefill_chunk,
        prefix_cache_mb=args.prefix_cache_mb,
        prefix_block_tokens=args.prefix_block)
    server = ServingServer(engine, host=args.host, port=args.port)

    async def go():
        import signal

        await server.start()
        print(json.dumps({
            "serving": args.model, "host": args.host, "port": server.port,
            "slots": args.slots, "max_queue": args.max_queue,
            "prefill_chunk": args.prefill_chunk,
            "prefix_cache_mb": args.prefix_cache_mb,
        }), flush=True)
        # Signal-driven shutdown INSIDE the loop: a raw KeyboardInterrupt
        # out of asyncio.run would cancel the engine task before the
        # drain, skipping the graceful stop and the summary line.
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        await stop.wait()
        await server.stop(drain=True)
        summary = {k: round(v, 6) for k, v in metrics.summary().items()}
        if engine.prefix_cache is not None:
            summary["prefix_cache"] = engine.prefix_cache.stats()
        if auditor is not None:
            summary["recompile_audit"] = auditor.report()
        print(json.dumps(summary), flush=True)

    try:
        asyncio.run(go())
    except KeyboardInterrupt:
        pass
    finally:
        if metrics.stream is not None:
            metrics.stream.close()
        if tracer is not None:
            tracer.export_chrome_trace(args.trace_out)
            print(json.dumps({"trace_out": args.trace_out}), flush=True)
    return 0


def cluster_main(args) -> int:
    """Multi-replica serving: N child processes (each a full ``serve``
    on an ephemeral port) behind a supervised router on ``--port``.
    Replica death -> capped-backoff restart; ``{"cmd": "reload",
    "weights": path}`` on the router rolls new weights with zero
    downtime. See docs/operations.md for the runbook."""
    import asyncio
    import signal

    from distkeras_tpu.serving.cluster import ProcessReplica, ServingCluster
    from distkeras_tpu.telemetry import MetricsRegistry

    def replica_args(i: int) -> list[str]:
        extra = [
            "--model", args.model, "--model-args", args.model_args,
            "--slots", str(args.slots),
            "--max-queue", str(args.max_queue),
            "--seed", str(args.seed),
            "--prefix-cache-mb", str(args.prefix_cache_mb),
            "--prefix-block", str(args.prefix_block),
        ]
        if args.weights:
            extra += ["--weights", args.weights]
        if args.top_k is not None:
            extra += ["--top-k", str(args.top_k)]
        if args.prefill_chunk is not None:
            extra += ["--prefill-chunk", str(args.prefill_chunk)]
        if args.audit_recompiles:
            extra += ["--audit-recompiles", args.audit_recompiles]
        if args.metrics_out:
            extra += ["--metrics-out", f"{args.metrics_out}.r{i}"]
        if args.trace_out:
            extra += ["--trace-out", f"{args.trace_out}.r{i}"]
        return extra

    def replica_env(i: int) -> dict[str, str]:
        env = {}
        for item in args.replica_env:
            key, sep, val = item.partition("=")
            if not sep:
                raise SystemExit(f"--replica-env needs KEY=VAL, got {item!r}")
            env[key] = val.replace("{i}", str(i))
        return env

    from distkeras_tpu.telemetry import enable_tracing

    # Parent-side spans cover the router hop (route / rolling_reload);
    # each replica writes its own engine timeline to {trace_out}.r{i}.
    tracer = enable_tracing() if args.trace_out else None
    registry = MetricsRegistry()
    cluster = ServingCluster(
        lambda i: ProcessReplica(replica_args(i), host=args.host,
                                 env=replica_env(i)),
        args.replicas, host=args.host, port=args.port, registry=registry,
        router_kwargs={
            "affinity_tokens": args.prefix_block,
            "affinity_slack": args.affinity_slack,
        })

    async def go():
        await cluster.start()
        print(json.dumps({
            "cluster": args.model, "host": args.host, "port": cluster.port,
            "replicas": {rid: {"host": info.host, "port": info.port}
                         for rid, info in cluster.replicas.items()},
            "slots": args.slots, "prefix_cache_mb": args.prefix_cache_mb,
        }), flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        try:
            await stop.wait()
        finally:
            # Even when the wait is cancelled (KeyboardInterrupt on
            # platforms without signal handlers), the replica children
            # must be reaped — they are real processes, not tasks.
            await cluster.stop()
        print(json.dumps({
            "restarts": {rid: info.restarts
                         for rid, info in cluster.replicas.items()},
            "router": registry.snapshot(),
        }), flush=True)

    try:
        asyncio.run(go())
    except KeyboardInterrupt:
        pass
    finally:
        if tracer is not None:
            tracer.export_chrome_trace(args.trace_out)
            print(json.dumps({"trace_out": args.trace_out}), flush=True)
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        return serve_main(argv[1:])
    if argv and argv[0] == "cluster":
        return serve_main(argv[1:], prog="cluster", default_replicas=2)
    ap = argparse.ArgumentParser(prog="distkeras_tpu.run")
    ap.add_argument("--config", required=True, help="TrainerConfig JSON file")
    ap.add_argument("--data", required=True, help=".npz (features/label) or CSV")
    ap.add_argument("--model", default="mnist_mlp", help=f"one of {sorted(MODEL_ZOO)}")
    ap.add_argument("--model-args", default="{}", help="JSON kwargs for the model fn")
    ap.add_argument("--out", default=None, help="path to save trained weights")
    ap.add_argument("--metrics-out", default=None, help="JSONL per-step metrics")
    ap.add_argument("--trace-out", default=None,
                    help="enable spans; write Chrome-trace JSON (Perfetto-"
                         "loadable) of the whole run here")
    ap.add_argument("--audit-recompiles", action="store_true",
                    help="count train-step compiles (+ triggering shapes); "
                         "report appears in the summary line")
    ap.add_argument("--shuffle", action="store_true")
    args = ap.parse_args(argv)

    from distkeras_tpu.telemetry import RecompileAuditor, enable_tracing
    from distkeras_tpu.tracing import MetricStream
    from distkeras_tpu.utils.config import TrainerConfig

    tracer = enable_tracing() if args.trace_out else None
    cfg = TrainerConfig.from_json(open(args.config).read())
    model = load_model(args.model, json.loads(args.model_args))
    ds = load_data(args.data, cfg.features_col, cfg.label_col)
    trainer = cfg.build(model)
    if args.metrics_out:
        trainer.metric_stream = MetricStream.to_jsonl(args.metrics_out)
    if args.audit_recompiles:
        trainer.auditor = RecompileAuditor()

    try:
        trained = trainer.train(ds, shuffle=args.shuffle)
    finally:
        # The JSONL stream owns a file handle; the trace is only useful
        # if it lands on disk even when training dies mid-run.
        if trainer.metric_stream is not None:
            trainer.metric_stream.close()
        if tracer is not None:
            tracer.export_chrome_trace(args.trace_out)
    summary = {
        "trainer": cfg.trainer,
        "steps": len(trainer.get_history()),
        "training_time_s": round(trainer.get_training_time(), 3),
        "averaged_history": {
            k: round(v, 5) for k, v in trainer.get_averaged_history().items()
        },
    }
    if args.audit_recompiles:
        summary["recompile_audit"] = trainer.auditor.report()
    if args.trace_out:
        summary["trace_out"] = args.trace_out
    if args.out:
        if isinstance(trained, list):  # EnsembleTrainer
            for i, t in enumerate(trained):
                t.save_weights(f"{args.out}.{i}")
            summary["saved"] = [f"{args.out}.{i}" for i in range(len(trained))]
        else:
            trained.save_weights(args.out)
            summary["saved"] = args.out
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
