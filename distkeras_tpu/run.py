"""CLI runner: ``python -m distkeras_tpu.run --config job.json --data d.npz``.

The executable form of a ``TrainerConfig`` — what a ``Job``/``Punchcard``
ships to a TPU host. The config JSON carries the trainer spec (see
:mod:`distkeras_tpu.utils.config`); data arrives as an ``.npz`` with
``features``/``label`` arrays or a headered CSV; the model comes from the
built-in zoo by name.

Example config:
    {"trainer": "ADAG", "worker_optimizer": "adam", "learning_rate": 1e-3,
     "num_workers": 4, "batch_size": 64, "num_epoch": 2,
     "communication_window": 12}

Online serving (``python -m distkeras_tpu.run serve --model gpt_tiny
--port 8500``) starts the continuous-batching TCP server
(:mod:`distkeras_tpu.serving`) over a causal LM from the zoo;
``serve --replicas N`` (or the ``cluster`` subcommand) starts N replica
processes behind a supervised router with automatic restarts and
zero-downtime rolling weight reloads
(:mod:`distkeras_tpu.serving.cluster`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

MODEL_ZOO = {
    "mnist_mlp": ("distkeras_tpu.models.mlp", "mnist_mlp"),
    "higgs_mlp": ("distkeras_tpu.models.mlp", "higgs_mlp"),
    "mnist_cnn": ("distkeras_tpu.models.cnn", "mnist_cnn"),
    "cifar10_cnn": ("distkeras_tpu.models.cnn", "cifar10_cnn"),
    "resnet18": ("distkeras_tpu.models.resnet", "resnet18"),
    "resnet50": ("distkeras_tpu.models.resnet", "resnet50"),
    "bert_tiny_mlm": ("distkeras_tpu.models.bert", "bert_tiny_mlm"),
    "bert_base_mlm": ("distkeras_tpu.models.bert", "bert_base_mlm"),
    "gpt_tiny": ("distkeras_tpu.models.bert", "gpt_tiny"),
    "gpt_small": ("distkeras_tpu.models.bert", "gpt_small"),
}


def load_model(name: str, kwargs: dict):
    import importlib

    if name not in MODEL_ZOO:
        raise SystemExit(f"unknown model {name!r}; known: {sorted(MODEL_ZOO)}")
    mod, fn = MODEL_ZOO[name]
    return getattr(importlib.import_module(mod), fn)(**kwargs)


def load_data(path: str, features_col: str, label_col: str):
    from distkeras_tpu.data.dataset import Dataset

    if path.endswith(".npz"):
        with np.load(path) as d:
            return Dataset.from_arrays(
                **{features_col: d["features"], label_col: d["label"]}
            )
    header = open(path).readline().strip().split(",")
    return Dataset.from_csv(
        path, features=[c for c in header if c != label_col], label=label_col,
        features_col=features_col, label_col=label_col,
    )


def _apply_force_host_devices(n: int | None) -> None:
    """``--force-host-devices N``: expose N virtual CPU devices by
    setting the XLA host-platform flag BEFORE jax initializes (it is
    read once at backend init). Single-threaded Eigen rides along —
    the virtual devices share ONE intra-op pool, and the tp all-reduces
    a sharded engine runs every layer can deadlock the rendezvous when
    pool-parallel kernels hold the pool (the
    ``utils.platform.ensure_virtual_cpu_flags`` failure mode; this
    helper replaces rather than raises the count, so it keeps its own
    env writer). If jax already initialized at a different count — an
    embedder imported it first — fail typed instead of silently
    serving on the wrong device count."""
    if not n:
        return
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   flags)
    flags += f" --xla_force_host_platform_device_count={int(n)}"
    if "--xla_cpu_multi_thread_eigen" not in flags:
        flags += " --xla_cpu_multi_thread_eigen=false"
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "jax" in sys.modules:
        import jax

        # Forced HOST devices only exist on the CPU platform; pin it
        # via jax.config (the reliable knob — an accelerator-container
        # sitecustomize may override the JAX_PLATFORMS env var and hang
        # in remote-backend init instead).
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backends already initialized: the count check decides
        if len(jax.devices()) != int(n):
            raise SystemExit(
                f"--force-host-devices {n}: jax already initialized "
                f"with {len(jax.devices())} device(s); set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n} in the "
                f"environment instead")


def _resolve_mesh(args):
    """Build the serving mesh ``--mesh``/``--mesh-shape`` ask for, or
    None. Bad specs and shapes that don't divide the visible device
    count become typed CLI errors (SystemExit) here — never a deep jax
    traceback out of the engine."""
    if not (getattr(args, "mesh", False)
            or getattr(args, "mesh_shape", None)):
        return None
    from distkeras_tpu.parallel.mesh import parse_mesh_shape, serving_mesh

    shape = None
    if args.mesh_shape:
        try:
            shape = parse_mesh_shape(args.mesh_shape)
        except ValueError as e:
            raise SystemExit(f"--mesh-shape: {e}")
    try:
        return serving_mesh(shape)
    except ValueError as e:
        raise SystemExit(f"--mesh: {e}")


def serve_main(argv=None, prog="serve", default_replicas=1) -> int:
    """``serve`` subcommand: continuous-batching TCP server over a causal
    LM from the zoo (random-init demo weights unless --weights given).
    ``--replicas N`` (or the ``cluster`` subcommand) instead starts N
    replica processes behind a supervised router on ``--port``."""
    ap = argparse.ArgumentParser(prog=f"distkeras_tpu.run {prog}")
    ap.add_argument("--model", default="gpt_tiny",
                    help="causal LM from the zoo (gpt_tiny/gpt_small)")
    ap.add_argument("--model-args", default="{}",
                    help="JSON kwargs for the model fn")
    ap.add_argument("--weights", default=None,
                    help="serialized-pytree weights (save_weights output); "
                         "random init when omitted")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8500, help="0 = ephemeral")
    ap.add_argument("--slots", type=int, default=4,
                    help="decode batch width (concurrent requests)")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="admission queue depth before queue_full rejects")
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="split prompt prefill into chunks of this many "
                         "tokens, one per decode tick — bounds the decode "
                         "stall (p99 ITL) a long prompt can cause; "
                         "default: monolithic prefill")
    ap.add_argument("--pipeline-depth", type=int, default=1,
                    help="decode pipeline depth: 1 (default) dispatches "
                         "tick N+1 before consuming tick N's tokens, so "
                         "host bookkeeping (streaming, admission, socket "
                         "reads) overlaps device compute; >=2 on a pp "
                         "mesh micro-batches the slots to keep every "
                         "stage busy (depth>=pp hides stage bubbles); "
                         "0 serializes "
                         "dispatch and harvest (the pre-pipeline "
                         "behavior). Greedy output is token-identical "
                         "either way — see docs/serving.md 'Decode "
                         "pipeline'")
    ap.add_argument("--prefix-cache-mb", type=float, default=0.0,
                    help="> 0 enables the device-resident prompt prefix "
                         "cache under this byte budget: shared prefixes "
                         "(system prompts, templates) splice cached KV "
                         "blocks instead of recomputing prefill")
    ap.add_argument("--prefix-block", type=int, default=16,
                    help="prefix-cache block granularity in tokens")
    ap.add_argument("--paged", action="store_true",
                    help="paged KV: decode slots allocate fixed-size "
                         "blocks from ONE shared pool (which doubles as "
                         "the prefix cache) via per-slot block tables — "
                         "capacity scales with resident tokens, the pool "
                         "may be oversubscribed (preempt-and-requeue), "
                         "and long-context requests chain blocks up to "
                         "the trained context")
    ap.add_argument("--constrained", action="store_true",
                    help="constrained decoding: compile the decode step "
                         "with a per-slot additive token-mask input so "
                         "requests may carry a 'constraint' automaton "
                         "(docs/serving.md 'Request kinds'). Requires "
                         "--paged/--kv-pool-mb; without this flag such "
                         "requests are rejected as bad_request")
    ap.add_argument("--kv-pool-mb", type=float, default=0.0,
                    help="paged-KV pool byte budget (MB); > 0 implies "
                         "--paged. See docs/serving.md 'KV pool sizing'")
    ap.add_argument("--kv-block-tokens", type=int, default=16,
                    help="paged-KV block granularity in tokens")
    ap.add_argument("--kv-host-tier-mb", type=float, default=0.0,
                    help="tiered KV cache: host-RAM spill tier byte "
                         "budget (MB). > 0: unreferenced hot blocks "
                         "evicted from the device pool spill to host "
                         "RAM (exact serialized KV bytes) and re-admit "
                         "on the next prefix hit instead of "
                         "re-prefilling. Requires --paged/--kv-pool-mb. "
                         "See docs/serving.md 'Tiered KV cache'")
    ap.add_argument("--kv-disk-tier-dir", default=None, metavar="DIR",
                    help="tiered KV cache: optional disk tier under the "
                         "host tier — host-tier evictions demote to "
                         "files in DIR instead of being dropped")
    ap.add_argument("--kv-disk-tier-mb", type=float, default=0.0,
                    help="disk tier byte budget (MB); must be > 0 for "
                         "the disk tier to hold anything")
    ap.add_argument("--kv-tier-watermark", type=float, default=0.8,
                    help="tier eviction low-watermark: an over-budget "
                         "tier evicts LRU entries down to this fraction "
                         "of its budget (batched eviction, not "
                         "per-put thrash)")
    ap.add_argument("--max-context", type=int, default=None,
                    help="cap per-request context below the trained "
                         "length; in dense mode also shrinks the "
                         "pre-reserved per-slot KV cache to this many "
                         "positions")
    ap.add_argument("--draft-model", default=None,
                    help="speculative decoding: a small causal LM from "
                         "the zoo drafts --spec-k tokens per tick and "
                         "ONE batched target call verifies them — "
                         "greedy output stays token-identical (exactly "
                         "with draft==target; a different draft can "
                         "differ only where the target scores two "
                         "tokens as numerically tied at its own "
                         "compute precision — see docs/serving.md "
                         "'Speculative decoding') while decode "
                         "throughput rises with the accept rate. Same "
                         "model+args as --model shares the target's "
                         "weights (the accept-rate sanity config); "
                         "otherwise the draft runs its own seed-init "
                         "weights unless --draft-weights")
    ap.add_argument("--draft-args", default="{}",
                    help="JSON kwargs for the draft model fn")
    ap.add_argument("--draft-weights", default=None,
                    help="serialized-pytree weights for the draft model")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens proposed per speculative tick")
    ap.add_argument("--mesh", action="store_true",
                    help="GSPMD tensor-parallel serving: shard the model "
                         "and its KV (dense caches or the paged pool "
                         "alike) over a device mesh — ONE replica spread "
                         "over every visible device (tp=<all>), greedy "
                         "output token-identical to the unsharded "
                         "engine. See docs/serving.md 'Sharded serving'")
    ap.add_argument("--mesh-shape", default=None, metavar="AXIS=N[,..]",
                    help="explicit serving mesh shape (implies --mesh), "
                         "e.g. 'tp=2'; the device product must divide "
                         "the visible device count. Bare N means tp=N")
    ap.add_argument("--force-host-devices", type=int, default=None,
                    metavar="N",
                    help="force the CPU host platform to expose N "
                         "virtual devices (sets XLA_FLAGS before jax "
                         "loads) — how a laptop/CI host runs --mesh "
                         "without real accelerators")
    ap.add_argument("--wire", default="auto",
                    choices=["auto", "bin1", "jsonl"],
                    help="front-door protocol policy: 'auto'/'bin1' "
                         "serve JSONL as always AND accept the "
                         "length-prefixed bin1 upgrade from clients "
                         "that offer it (cluster mode also negotiates "
                         "bin1 to each replica); 'jsonl' pins "
                         "everything to the original protocol — the "
                         "rollback knob")
    ap.add_argument("--tenant-quota", action="append", default=None,
                    metavar="TENANT=TOK_S",
                    help="repeatable; per-tenant token-rate quota in "
                         "tokens/second — an over-quota tenant gets a "
                         "typed tenant_over_quota reject at submit, "
                         "never a mid-stream kill")
    ap.add_argument("--tenant-weight", action="append", default=None,
                    metavar="TENANT=W",
                    help="repeatable; per-tenant weighted-fair-queueing "
                         "weight (default 1.0) — within a priority "
                         "class, a weight-2 tenant is offered twice "
                         "the token bandwidth under contention")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=default_replicas,
                    help="> 1: start this many replica processes behind a "
                         "supervised router on --port (least-outstanding "
                         "routing with prefix-cache affinity, automatic "
                         "restarts, rolling weight reloads)")
    ap.add_argument("--roles", default=None, metavar="prefill=N,decode=M",
                    help="disaggregated cluster: N prefill replicas + M "
                         "decode replicas (implies cluster mode, "
                         "overrides --replicas; requires --paged/"
                         "--kv-pool-mb). The router prefills each "
                         "prompt family ONCE on its prefill replica "
                         "and decode replicas adopt the KV blocks over "
                         "the wire (KVBLK frames) — chunked prefill "
                         "stops stealing decode ticks, hot prefixes "
                         "are prefilled once per FLEET, and every "
                         "transfer failure falls back to monolithic "
                         "serving. See docs/serving.md 'Disaggregated "
                         "serving'")
    ap.add_argument("--kv-push", action="store_true",
                    help="disaggregated cluster (--roles): the router "
                         "push-schedules prefill→decode KV transfers — "
                         "right after each prefill handoff it tells the "
                         "prefill replica to PUSH the blocks at the "
                         "picked decode replica while that replica "
                         "works on earlier requests, replacing the "
                         "adopt-time pull; the fleet cache directory "
                         "skips the transfer entirely when the decode "
                         "replica already holds the prefix family. "
                         "Every miss falls back to pull, then "
                         "monolithic — counted, never a client error")
    ap.add_argument("--affinity-slack", type=int, default=4,
                    help="cluster mode: max outstanding-request imbalance "
                         "the prefix-affinity pin may create before plain "
                         "least-outstanding routing wins")
    ap.add_argument("--replica-env", action="append", default=[],
                    metavar="KEY=VAL",
                    help="cluster mode, repeatable: extra env var for each "
                         "replica child; '{i}' expands to the replica "
                         "index — the device-partitioning hook (e.g. "
                         "CUDA_VISIBLE_DEVICES={i} so N replicas on one "
                         "accelerator host each claim one chip instead of "
                         "all of them)")
    ap.add_argument("--metrics-out", default=None,
                    help="JSONL per-iteration serving metrics")
    ap.add_argument("--trace-out", default=None,
                    help="enable spans; write Chrome-trace JSON (Perfetto-"
                         "loadable) here on shutdown")
    ap.add_argument("--audit-recompiles", nargs="?", const="report",
                    choices=["report", "arm"], default=None,
                    help="count compiles per jitted program (report at "
                         "exit); 'arm' additionally fails loudly if the "
                         "decode step ever recompiles after its first "
                         "iteration")
    ap.add_argument("--request-trace", type=int, default=None, metavar="N",
                    help="> 0: keep the last N per-request timeline "
                         "records queryable via the tracez verb / "
                         "`run.py debugz --trace ID`. Default: off for "
                         "serve, 512 for cluster mode; 0 disables "
                         "explicitly")
    ap.add_argument("--request-trace-out", default=None,
                    help="write the request-timeline store as Chrome-"
                         "trace JSON (one lane per request) on shutdown; "
                         "implies --request-trace")
    ap.add_argument("--wide-events", type=int, default=4096, metavar="N",
                    help="per-request wide-event ring capacity (one flat "
                         "~40-column record per finished request, "
                         "queryable via the queryz verb / `run.py "
                         "queryz`); 0 disables")
    ap.add_argument("--flight-recorder", type=int, default=None,
                    metavar="N",
                    help="> 0: arm the flight recorder with an N-event "
                         "black box of recent engine state + request "
                         "timelines. Default: off for serve (unless "
                         "--flight-dump is given), 256 for cluster "
                         "mode; 0 disables explicitly")
    ap.add_argument("--flight-dump", default=None,
                    help="where the flight recorder dumps on crash/exit "
                         "(the replica's 'last words' file the cluster "
                         "supervisor collects); implies --flight-recorder")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="request-latency SLO in ms: slower requests bump "
                         "serving_slo_violations_total and pin their full "
                         "timeline as a flight-recorder slow exemplar")
    ap.add_argument("--profile-out", default=None, metavar="DIR",
                    help="capture a jax.profiler (XLA) trace of the whole "
                         "serve into this directory — the device-timeline "
                         "complement to the host spans --trace-out writes")
    ap.add_argument("--flight-dir", default=None,
                    help="cluster mode: directory for per-replica flight-"
                         "recorder dumps (default: a fresh temp dir, "
                         "printed in the banner); each replica child gets "
                         "--flight-dump <dir>/flight-r<i>.json and the "
                         "supervisor collects a dead replica's file into "
                         "its restart log")
    args = ap.parse_args(argv)
    # BEFORE anything imports jax: the forced-device-count XLA flag is
    # read once at backend init, so it must hit the environment first.
    _apply_force_host_devices(args.force_host_devices)
    if args.replicas > 1 or args.roles:
        return cluster_main(args)

    import asyncio

    from distkeras_tpu.serving import (
        ServingEngine, ServingMetrics, ServingServer,
    )
    from distkeras_tpu.telemetry import RecompileAuditor, enable_tracing
    from distkeras_tpu.tracing import MetricStream

    from distkeras_tpu.telemetry import MetricsRegistry

    tracer = enable_tracing() if args.trace_out else None
    mesh = _resolve_mesh(args)
    model = load_model(args.model, json.loads(args.model_args))
    variables = model.init(args.seed)
    weight_version = None
    if args.weights:
        from distkeras_tpu.checkpoint import load_weights_file_with_provenance

        variables, weight_version = load_weights_file_with_provenance(
            args.weights, like=variables)
    # One registry behind everything this process publishes — serving
    # metrics, the scheduler, the stream's last-value gauges, the auditor
    # — so a metricsz scrape shows the whole picture.
    registry = MetricsRegistry()
    metrics = ServingMetrics(
        MetricStream.to_jsonl(args.metrics_out, registry=registry)
        if args.metrics_out else None,
        registry=registry)
    auditor = (RecompileAuditor(registry=registry)
               if args.audit_recompiles else None)
    from distkeras_tpu.telemetry import (
        FlightRecorder, TailRetention, TraceStore,
    )

    # None = unset (flag defaults apply); an EXPLICIT 0 always disables.
    trace_cap = args.request_trace
    if trace_cap is None and args.request_trace_out:
        trace_cap = 512
    # Tail-based retention rides every armed trace store: errors, SLO
    # breaches, per-kind latency tails, rare tenants, and a 1/N
    # baseline survive the sliding window in a keeper reservoir.
    trace_store = (TraceStore(trace_cap, retention=TailRetention())
                   if trace_cap else None)
    recorder_cap = args.flight_recorder
    if recorder_cap is None and args.flight_dump:
        recorder_cap = 256
    recorder = None
    if recorder_cap:
        recorder = FlightRecorder(
            capacity=recorder_cap,
            dump_path=args.flight_dump,
            source=f"serve:{args.model}:pid{os.getpid()}")
    # --paged with no explicit budget gets a sane default pool; an
    # explicit --kv-pool-mb implies --paged.
    kv_pool_mb = args.kv_pool_mb or (64.0 if args.paged else 0.0)
    if args.kv_host_tier_mb and not kv_pool_mb:
        raise SystemExit("--kv-host-tier-mb requires --paged or "
                         "--kv-pool-mb: the host tier spills paged-KV "
                         "blocks")
    if args.constrained and not kv_pool_mb:
        raise SystemExit("--constrained requires --paged or "
                         "--kv-pool-mb: the token-mask decode step "
                         "runs on the paged pool")
    draft_model = draft_variables = None
    if args.draft_model:
        draft_kwargs = json.loads(args.draft_args)
        if "vocab_size" not in draft_kwargs:
            # Draft proposals are TARGET token ids, so the draft must
            # share the target's vocab — default it so the documented
            # zoo pairing (`--model gpt_small --draft-model gpt_tiny`)
            # works without hand-passing 50257 through --draft-args.
            try:
                draft_model = load_model(
                    args.draft_model,
                    {**draft_kwargs, "vocab_size": model.output_dim})
            except TypeError:  # model fn without a vocab_size kwarg
                draft_model = None
        if draft_model is None:
            draft_model = load_model(args.draft_model, draft_kwargs)
        if (args.draft_model == args.model
                and json.loads(args.draft_args) == json.loads(
                    args.model_args)
                and not args.draft_weights):
            # Identical spec with no weights of its own: the draft IS
            # the target (the draft==target sanity config — acceptance
            # ~100%, the speedup is pure dispatch amortization).
            draft_variables = variables
        else:
            draft_variables = draft_model.init(args.seed)
            if args.draft_weights:
                from distkeras_tpu.checkpoint import load_weights_file

                draft_variables = load_weights_file(
                    args.draft_weights, like=draft_variables)
    engine = ServingEngine(
        model, variables, slots=args.slots, max_queue=args.max_queue,
        top_k=args.top_k, metrics=metrics, seed=args.seed,
        auditor=auditor,
        arm_auditor_after_warmup=args.audit_recompiles == "arm",
        prefill_chunk=args.prefill_chunk,
        prefix_cache_mb=0.0 if kv_pool_mb else args.prefix_cache_mb,
        prefix_block_tokens=args.prefix_block,
        kv_pool_mb=kv_pool_mb,
        kv_block_tokens=args.kv_block_tokens,
        kv_host_tier_mb=args.kv_host_tier_mb,
        kv_disk_tier_dir=args.kv_disk_tier_dir,
        kv_disk_tier_mb=args.kv_disk_tier_mb,
        kv_tier_watermark=args.kv_tier_watermark,
        constrained=args.constrained,
        max_context=args.max_context,
        draft_model=draft_model, draft_variables=draft_variables,
        spec_k=args.spec_k, mesh=mesh,
        pipeline_depth=args.pipeline_depth,
        trace_store=trace_store, flight_recorder=recorder,
        wide_events=args.wide_events,
        slo_s=args.slo_ms / 1e3 if args.slo_ms else None,
        weight_version=weight_version,
        tenant_quotas=_parse_tenant_rates(args.tenant_quota,
                                          "--tenant-quota"),
        tenant_weights=_parse_tenant_rates(args.tenant_weight,
                                           "--tenant-weight"))
    server = ServingServer(
        engine, host=args.host, port=args.port,
        wire_mode="jsonl" if args.wire == "jsonl" else "auto")

    async def go():
        import signal

        await server.start()
        print(json.dumps({
            "serving": args.model, "host": args.host, "port": server.port,
            "slots": args.slots, "max_queue": args.max_queue,
            "prefill_chunk": args.prefill_chunk,
            "prefix_cache_mb": args.prefix_cache_mb,
            "kv_pool_mb": kv_pool_mb,
            "kv_pool_blocks": (engine.kv_pool.capacity
                               if engine.kv_pool is not None else 0),
            "draft_model": args.draft_model,
            "spec_k": args.spec_k if args.draft_model else 0,
            "mesh": engine.mesh_info(),
        }), flush=True)
        # Signal-driven shutdown INSIDE the loop: a raw KeyboardInterrupt
        # out of asyncio.run would cancel the engine task before the
        # drain, skipping the graceful stop and the summary line.
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        await stop.wait()
        await server.stop(drain=True)
        summary = {k: round(v, 6) for k, v in metrics.summary().items()}
        if engine.prefix_cache is not None:
            summary["prefix_cache"] = engine.prefix_cache.stats()
        if engine.kv_pool is not None:
            summary["kv_pool"] = engine.kv_pool.stats()
        if auditor is not None:
            summary["recompile_audit"] = auditor.report()
        print(json.dumps(summary), flush=True)

    import contextlib

    from distkeras_tpu.telemetry import profile_trace

    profiler = (profile_trace(args.profile_out) if args.profile_out
                else contextlib.nullcontext())
    try:
        with profiler:
            asyncio.run(go())
    except KeyboardInterrupt:
        pass
    finally:
        if metrics.stream is not None:
            metrics.stream.close()
        if tracer is not None:
            tracer.export_chrome_trace(args.trace_out)
            print(json.dumps({"trace_out": args.trace_out}), flush=True)
        # Graceful-exit black box: the crash path already dumped inside
        # the engine loop; this covers SIGTERM drains so the file exists
        # either way.
        if recorder is not None and recorder.dump_path:
            try:
                recorder.dump()
            except OSError:
                pass
        if trace_store is not None and args.request_trace_out:
            trace_store.export_chrome_trace(args.request_trace_out)
            print(json.dumps(
                {"request_trace_out": args.request_trace_out}), flush=True)
    return 0


def _parse_tenant_rates(items, flag: str) -> dict | None:
    """Repeated ``TENANT=VALUE`` CLI items into a dict (None when the
    flag was never given). Bad input is a typed CLI error, never a deep
    float() traceback out of the engine ctor."""
    if not items:
        return None
    out = {}
    for item in items:
        name, sep, value = str(item).partition("=")
        if not sep or not name:
            raise SystemExit(f"{flag} needs TENANT=VALUE, got {item!r}")
        try:
            out[name] = float(value)
        except ValueError:
            raise SystemExit(
                f"{flag}: bad numeric value in {item!r}") from None
    return out


def _serving_config_flags(args) -> list[str]:
    """Serving-engine configuration flags a parent process forwards to
    every replica child — ONE builder shared by ``cluster`` and
    ``deploy``, so the whole fleet (and, in deploy's case, the canary
    replica, which is a drained member of that same fleet) runs the
    configuration the operator asked for. Before deploy used this, its
    canary always validated candidates on the dense one-token default —
    a paged or speculative production config shipped unvetted."""
    extra = [
        "--prefix-cache-mb", str(args.prefix_cache_mb),
        "--prefix-block", str(args.prefix_block),
    ]
    if args.top_k is not None:
        extra += ["--top-k", str(args.top_k)]
    if args.prefill_chunk is not None:
        extra += ["--prefill-chunk", str(args.prefill_chunk)]
    if getattr(args, "pipeline_depth", None) is not None:
        extra += ["--pipeline-depth", str(args.pipeline_depth)]
    if args.paged or args.kv_pool_mb:
        if args.paged:
            extra += ["--paged"]
        extra += ["--kv-pool-mb", str(args.kv_pool_mb),
                  "--kv-block-tokens", str(args.kv_block_tokens)]
        if getattr(args, "kv_host_tier_mb", 0.0):
            extra += ["--kv-host-tier-mb", str(args.kv_host_tier_mb),
                      "--kv-tier-watermark", str(args.kv_tier_watermark)]
            if getattr(args, "kv_disk_tier_dir", None):
                # One shared dir is safe: spill file names carry the
                # replica pid.
                extra += ["--kv-disk-tier-dir", args.kv_disk_tier_dir,
                          "--kv-disk-tier-mb", str(args.kv_disk_tier_mb)]
        if getattr(args, "constrained", False):
            extra += ["--constrained"]
    if args.max_context is not None:
        extra += ["--max-context", str(args.max_context)]
    if args.draft_model:
        extra += ["--draft-model", args.draft_model,
                  "--draft-args", args.draft_args,
                  "--spec-k", str(args.spec_k)]
        if args.draft_weights:
            extra += ["--draft-weights", args.draft_weights]
    # Sharded serving: every replica child builds the same mesh. The
    # forced-device-count flag rides along so a child process sees the
    # same virtual device world its parent validated against (parent
    # XLA_FLAGS inherit anyway; the explicit flag keeps a copied command
    # line self-contained).
    if getattr(args, "mesh_shape", None):
        extra += ["--mesh-shape", str(args.mesh_shape)]
    elif getattr(args, "mesh", False):
        extra += ["--mesh"]
    if getattr(args, "force_host_devices", None):
        extra += ["--force-host-devices", str(args.force_host_devices)]
    # Front-door wire policy + multi-tenant QoS ride to every replica
    # (and therefore through deploy's canary), so the production wire
    # configuration is exactly what gets validated.
    if getattr(args, "wire", None):
        extra += ["--wire", args.wire]
    for item in getattr(args, "tenant_quota", None) or []:
        extra += ["--tenant-quota", str(item)]
    for item in getattr(args, "tenant_weight", None) or []:
        extra += ["--tenant-weight", str(item)]
    if getattr(args, "wide_events", None) is not None:
        extra += ["--wide-events", str(args.wide_events)]
    return extra


def _parse_roles(spec: str | None) -> list[str] | None:
    """``--roles prefill=N,decode=M`` via the ONE shared parser
    (``serving.cluster.parse_roles``); bad input is a typed CLI exit,
    never a deep traceback out of the supervisor."""
    from distkeras_tpu.serving.cluster import parse_roles

    try:
        return parse_roles(spec)
    except ValueError as e:
        raise SystemExit(f"--roles: {e}") from None


def cluster_main(args) -> int:
    """Multi-replica serving: N child processes (each a full ``serve``
    on an ephemeral port) behind a supervised router on ``--port``.
    Replica death -> capped-backoff restart; ``{"cmd": "reload",
    "weights": path}`` on the router rolls new weights with zero
    downtime. ``--roles prefill=N,decode=M`` splits the fleet into
    prefill and decode roles with KV block migration between them.
    See docs/operations.md for the runbook."""
    import asyncio
    import signal
    import tempfile

    # Typed mesh validation in the PARENT: a bad --mesh-shape must fail
    # the cluster command with one clear line, not N crash-looping
    # replica children. (The children re-validate on their own devices.)
    _resolve_mesh(args)
    roles = _parse_roles(getattr(args, "roles", None))
    if roles is not None:
        if not (args.paged or args.kv_pool_mb):
            raise SystemExit(
                "--roles requires --paged or --kv-pool-mb: KV block "
                "migration (the prefill->decode handoff) only exists "
                "on the paged pool")
        args.replicas = len(roles)
    if getattr(args, "kv_push", False) and roles is None:
        raise SystemExit("--kv-push requires --roles: push scheduling "
                         "rides the prefill->decode handoff")

    from distkeras_tpu.serving.cluster import ProcessReplica, ServingCluster
    from distkeras_tpu.telemetry import MetricsRegistry

    # Observability defaults are ON in cluster mode: per-request tracing
    # and flight recording cost per-REQUEST bookkeeping only (the
    # per-token path is untouched), and a fleet without them cannot
    # answer "where did this request go" — the reason the cluster
    # subcommand exists is operating at that scale.
    flight_dir = args.flight_dir or tempfile.mkdtemp(
        prefix="distkeras-flight-")
    os.makedirs(flight_dir, exist_ok=True)

    def flight_dump(i: int) -> str:
        return os.path.join(flight_dir, f"flight-r{i}.json")

    def replica_args(i: int) -> list[str]:
        extra = [
            "--model", args.model, "--model-args", args.model_args,
            "--slots", str(args.slots),
            "--max-queue", str(args.max_queue),
            "--seed", str(args.seed),
            *_serving_config_flags(args),
            "--request-trace",
            str(512 if args.request_trace is None else args.request_trace),
            "--flight-recorder",
            str(256 if args.flight_recorder is None else args.flight_recorder),
            "--flight-dump", flight_dump(i),
        ]
        if args.weights:
            extra += ["--weights", args.weights]
        if args.audit_recompiles:
            extra += ["--audit-recompiles", args.audit_recompiles]
        if args.slo_ms is not None:
            extra += ["--slo-ms", str(args.slo_ms)]
        if args.metrics_out:
            extra += ["--metrics-out", f"{args.metrics_out}.r{i}"]
        if args.trace_out:
            extra += ["--trace-out", f"{args.trace_out}.r{i}"]
        if args.profile_out:
            # Each replica is its own jax process: per-replica profiler
            # dirs, or N children race on one XLA trace session.
            extra += ["--profile-out",
                      os.path.join(args.profile_out, f"r{i}")]
        return extra

    def replica_env(i: int) -> dict[str, str]:
        env = {}
        for item in args.replica_env:
            key, sep, val = item.partition("=")
            if not sep:
                raise SystemExit(f"--replica-env needs KEY=VAL, got {item!r}")
            env[key] = val.replace("{i}", str(i))
        return env

    from distkeras_tpu.telemetry import enable_tracing

    # Parent-side spans cover the router hop (route / rolling_reload);
    # each replica writes its own engine timeline to {trace_out}.r{i}.
    tracer = enable_tracing() if args.trace_out else None
    registry = MetricsRegistry()
    cluster = ServingCluster(
        lambda i: ProcessReplica(replica_args(i), host=args.host,
                                 env=replica_env(i),
                                 last_words_path=flight_dump(i)),
        args.replicas, host=args.host, port=args.port, registry=registry,
        roles=roles,
        router_kwargs={
            "affinity_tokens": args.prefix_block,
            "affinity_slack": args.affinity_slack,
            "wire_mode": "jsonl" if args.wire == "jsonl" else "auto",
            "trace_capacity":
                512 if args.request_trace is None else args.request_trace,
            # Handoff threshold tracks the KV BLOCK size, not the
            # affinity prefix: a prompt shorter than one block exports
            # nothing, so handing it off would pay two prefills + two
            # round trips for a guaranteed peer_miss.
            **({"min_handoff_tokens": args.kv_block_tokens}
               if roles is not None else {}),
            **({"kv_push": True} if getattr(args, "kv_push", False)
               else {}),
        })

    async def go():
        await cluster.start()
        print(json.dumps({
            "cluster": args.model, "host": args.host, "port": cluster.port,
            "replicas": {rid: {"host": info.host, "port": info.port,
                               "role": info.role}
                         for rid, info in cluster.replicas.items()},
            "slots": args.slots, "prefix_cache_mb": args.prefix_cache_mb,
            "flight_dir": flight_dir,
        }), flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        try:
            await stop.wait()
        finally:
            # Even when the wait is cancelled (KeyboardInterrupt on
            # platforms without signal handlers), the replica children
            # must be reaped — they are real processes, not tasks.
            await cluster.stop()
        print(json.dumps({
            "restarts": {rid: info.restarts
                         for rid, info in cluster.replicas.items()},
            "restart_log": cluster.supervisor.restart_log_entries(),
            "router": registry.snapshot(),
        }), flush=True)

    try:
        asyncio.run(go())
    except KeyboardInterrupt:
        pass
    finally:
        if tracer is not None:
            tracer.export_chrome_trace(args.trace_out)
            print(json.dumps({"trace_out": args.trace_out}), flush=True)
    return 0


def deploy_main(argv=None) -> int:
    """``deploy`` subcommand: the continuous-deployment loop — a
    ProcessReplica serving fleet behind the supervised router, plus a
    :class:`~distkeras_tpu.deploy.controller.DeployController` watching
    a publish directory. Every version a trainer publishes there
    (``run.py train --publish-dir``) is validated, canaried on one
    drained replica against a golden prompt set, rolled through the
    fleet with zero downtime, and rolled back + quarantined if anything
    regresses. Inspect live state with ``run.py deployz``."""
    ap = argparse.ArgumentParser(prog="distkeras_tpu.run deploy")
    ap.add_argument("--watch-dir", required=True, metavar="DIR",
                    help="publish directory to watch (the trainer's "
                         "--publish-dir). With no manifest yet, the "
                         "fleet bootstraps on (and publishes) seed-init "
                         "weights as v1")
    ap.add_argument("--model", default="gpt_tiny",
                    help="causal LM from the zoo (gpt_tiny/gpt_small)")
    ap.add_argument("--model-args", default="{}",
                    help="JSON kwargs for the model fn")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8500,
                    help="router front port (0 = ephemeral)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    # The fleet's REAL serving configuration, forwarded to every
    # replica (the canary is a drained member of this same fleet, so a
    # candidate is validated under the configuration production
    # actually runs — paged KV, chunked prefill, speculation and all —
    # not the dense one-token default).
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="replica chunked-prefill size (tokens)")
    ap.add_argument("--pipeline-depth", type=int, default=1,
                    help="replica decode pipeline depth (1 overlaps host "
                         "bookkeeping with device compute; >=2 "
                         "micro-batches a pp mesh; 0 serializes)")
    ap.add_argument("--prefix-cache-mb", type=float, default=0.0,
                    help="replica prefix-cache byte budget (MB)")
    ap.add_argument("--prefix-block", type=int, default=16,
                    help="prefix-cache block granularity (tokens)")
    ap.add_argument("--paged", action="store_true",
                    help="replicas serve with paged KV")
    ap.add_argument("--kv-pool-mb", type=float, default=0.0,
                    help="replica paged-KV pool budget (MB); > 0 "
                         "implies --paged")
    ap.add_argument("--kv-block-tokens", type=int, default=16)
    ap.add_argument("--max-context", type=int, default=None)
    ap.add_argument("--draft-model", default=None,
                    help="replicas serve with speculative decoding "
                         "(this zoo model drafts --spec-k tokens/tick)")
    ap.add_argument("--draft-args", default="{}")
    ap.add_argument("--draft-weights", default=None)
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--mesh", action="store_true",
                    help="replicas serve GSPMD tensor-parallel over "
                         "every visible device (see serve --mesh); the "
                         "canary validates candidates under the same "
                         "sharded config production runs")
    ap.add_argument("--mesh-shape", default=None, metavar="AXIS=N[,..]",
                    help="explicit per-replica serving mesh shape "
                         "(implies --mesh), e.g. 'tp=2'")
    ap.add_argument("--force-host-devices", type=int, default=None,
                    metavar="N",
                    help="expose N virtual CPU devices to every replica "
                         "(CI / laptop sharded-fleet runs)")
    ap.add_argument("--golden", type=int, default=4,
                    help="golden prompt count the canary replica must "
                         "serve (twice each, identical greedy output, "
                         "within the latency budget); 0 disables "
                         "replica-side scoring")
    ap.add_argument("--golden-len", type=int, default=8,
                    help="golden prompt length in tokens")
    ap.add_argument("--golden-new-tokens", type=int, default=4,
                    help="tokens decoded per golden prompt")
    ap.add_argument("--canary-latency-ms", type=float, default=30000.0,
                    help="per-golden-prompt canary latency budget")
    ap.add_argument("--poll-ms", type=float, default=500.0,
                    help="manifest poll interval")
    ap.add_argument("--publish-keep", type=int, default=5,
                    help="retention for the bootstrap publish")
    ap.add_argument("--audit-recompiles", nargs="?", const="arm",
                    choices=["report", "arm", "off"], default="arm",
                    help="replica recompile auditing (default: arm — a "
                         "decode retrace under weight churn fails "
                         "loudly; 'off' disables)")
    ap.add_argument("--replica-env", action="append", default=[],
                    metavar="KEY=VAL",
                    help="repeatable; extra env per replica child, {i} "
                         "expands to the index (device partitioning)")
    ap.add_argument("--wire", default="auto",
                    choices=["auto", "bin1", "jsonl"],
                    help="front-door protocol policy, forwarded to every "
                         "replica AND applied to the deploy router — the "
                         "canary validates candidates under the "
                         "production wire configuration")
    ap.add_argument("--tenant-quota", action="append", default=None,
                    metavar="TENANT=TOK_S",
                    help="repeatable; per-tenant token-rate quotas, "
                         "forwarded to every replica")
    ap.add_argument("--tenant-weight", action="append", default=None,
                    metavar="TENANT=W",
                    help="repeatable; per-tenant fair-queueing weights, "
                         "forwarded to every replica")
    ap.add_argument("--tenant", default="canary",
                    help="client-side tenant id the canary's golden "
                         "requests run under — keep it OUT of the "
                         "production quota set (a quota-shed canary "
                         "would fail every deploy) and it makes canary "
                         "traffic attributable in every tenant metric")
    args = ap.parse_args(argv)
    _apply_force_host_devices(args.force_host_devices)
    # Typed parent-side validation; the controller also scores golden
    # batches under this mesh (shard-then-place) when the fleet shards.
    deploy_mesh = _resolve_mesh(args)

    import asyncio
    import signal

    from distkeras_tpu.checkpoint import publish_weights, read_manifest
    from distkeras_tpu.deploy.harness import wire_controller
    from distkeras_tpu.serving.cluster import ProcessReplica, ServingCluster
    from distkeras_tpu.telemetry import MetricsRegistry

    model = load_model(args.model, json.loads(args.model_args))
    manifest = read_manifest(args.watch_dir)
    if manifest is None or not os.path.exists(manifest.get("path") or ""):
        # Nothing (usable) published yet: bootstrap the directory with
        # seed-init weights so the fleet boots on a FILE (the
        # controller's last-good rollback target must exist from the
        # first deploy). The exists-check also covers a restart whose
        # manifest still names a file the controller quarantined or the
        # publisher pruned — a fresh publish beats a crash-looping boot.
        manifest = publish_weights(
            args.watch_dir, model.init(args.seed),
            meta={"bootstrap": True}, keep=args.publish_keep)
        print(json.dumps({"bootstrap_published": manifest["path"],
                          "version": manifest["version"]}), flush=True)
    boot_weights = manifest["path"]

    def replica_args(i: int) -> list[str]:
        # No --weights: replicas boot random-init and the supervisor
        # reloads the fleet's current_weights (a controller-STAGED
        # file) before each becomes routable — initial start and every
        # later restart converge on the deployed version through one
        # path, immune to the watch dir's retention pruning the
        # original boot file.
        extra = [
            "--model", args.model, "--model-args", args.model_args,
            "--slots", str(args.slots),
            "--max-queue", str(args.max_queue),
            "--seed", str(args.seed),
            *_serving_config_flags(args),
            "--request-trace", "512",
            "--flight-recorder", "256",
        ]
        if args.audit_recompiles != "off":
            extra += ["--audit-recompiles", args.audit_recompiles]
        return extra

    def replica_env(i: int) -> dict[str, str]:
        env = {}
        for item in args.replica_env:
            key, sep, val = item.partition("=")
            if not sep:
                raise SystemExit(
                    f"--replica-env needs KEY=VAL, got {item!r}")
            env[key] = val.replace("{i}", str(i))
        return env

    registry = MetricsRegistry()
    cluster = ServingCluster(
        lambda i: ProcessReplica(replica_args(i), host=args.host,
                                 env=replica_env(i)),
        args.replicas, host=args.host, port=args.port, registry=registry,
        router_kwargs={
            "wire_mode": "jsonl" if args.wire == "jsonl" else "auto"})

    async def go():
        # Controller first: its ctor stages the boot weights, and the
        # supervisor must know the fleet's current_weights BEFORE the
        # replicas start (each is brought to it pre-READY).
        controller = wire_controller(
            cluster.router, args.watch_dir, model=model,
            vocab=model.output_dim, golden_count=args.golden,
            golden_len=args.golden_len,
            golden_new_tokens=args.golden_new_tokens, seed=args.seed,
            registry=registry, mesh=deploy_mesh,
            canary_latency_s=args.canary_latency_ms / 1e3,
            poll_interval_s=args.poll_ms / 1e3,
            canary_tenant=args.tenant,
            initial_weights=boot_weights)
        cluster.supervisor.current_weights = (
            (controller.last_good or {}).get("path") or boot_weights)
        await cluster.start()
        controller_task = asyncio.get_running_loop().create_task(
            controller.run(), name="deploy-controller")
        print(json.dumps({
            "deploy": args.model, "host": args.host, "port": cluster.port,
            "watch_dir": args.watch_dir,
            "boot_weights": boot_weights,
            "replicas": {rid: {"host": info.host, "port": info.port}
                         for rid, info in cluster.replicas.items()},
        }), flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        try:
            await stop.wait()
        finally:
            controller.stop()
            try:
                await asyncio.wait_for(controller_task, 10.0)
            except asyncio.TimeoutError:
                controller_task.cancel()
            await cluster.stop()
        print(json.dumps({"deployz": controller.deployz()}), flush=True)

    try:
        asyncio.run(go())
    except KeyboardInterrupt:
        pass
    return 0


def deployz_main(argv=None) -> int:
    """``deployz`` subcommand: fetch and pretty-print a live deploy
    controller's state page (current/last-good/candidate versions,
    deploy history ring, quarantine records) from a ``run.py deploy``
    router. ``--json`` prints the raw payload for scripts."""
    import asyncio

    ap = argparse.ArgumentParser(prog="distkeras_tpu.run deployz")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8500,
                    help="the deploy router's front port")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON payload instead of the pretty page")
    args = ap.parse_args(argv)

    from distkeras_tpu.serving import ServingClient, ServingError
    from distkeras_tpu.serving.debugz import format_deployz

    async def go():
        async with ServingClient(args.host, args.port,
                                 max_retries=0) as client:
            return await client.deployz()

    try:
        payload = asyncio.run(go())
    except (OSError, ConnectionError) as e:
        print(f"deployz: cannot reach {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 1
    except ServingError as e:
        print(f"deployz: server refused: {e}", file=sys.stderr)
        return 1
    print(json.dumps(payload, indent=1) if args.json
          else format_deployz(payload))
    return 0


def debugz_main(argv=None) -> int:
    """``debugz`` subcommand: fetch and pretty-print a live server's (or
    router's) introspection page — slot table, queue ages, prefix-cache
    occupancy, replica table with restart log — or, with ``--trace ID``,
    the merged cross-process timeline of one request. ``--json`` prints
    the raw payload for scripts."""
    import asyncio

    ap = argparse.ArgumentParser(prog="distkeras_tpu.run debugz")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8500,
                    help="a serving server's port, or a cluster router's "
                         "front port (fleet-aggregated page)")
    ap.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="fetch ONE request's (merged) timeline instead "
                         "of the debugz page")
    ap.add_argument("--recent", type=int, default=None, metavar="N",
                    help="list the N most recent request timelines")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON payload instead of the pretty page")
    args = ap.parse_args(argv)

    from distkeras_tpu.serving import ServingClient, ServingError
    from distkeras_tpu.serving.debugz import format_debugz, format_tracez

    async def go():
        async with ServingClient(args.host, args.port,
                                 max_retries=0) as client:
            if args.trace is not None:
                return "tracez", await client.tracez(args.trace)
            if args.recent is not None:
                return "tracez", await client.tracez(n=args.recent)
            return "debugz", await client.debugz()

    try:
        kind, payload = asyncio.run(go())
    except (OSError, ConnectionError) as e:
        print(f"debugz: cannot reach {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 1
    except ServingError as e:
        print(f"debugz: server refused: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(payload, indent=1))
    else:
        print(format_tracez(payload) if kind == "tracez"
              else format_debugz(payload))
    return 0


def queryz_main(argv=None) -> int:
    """``queryz`` subcommand: filter / group / aggregate the wide-event
    per-request store of a live server — or a whole fleet through its
    router, where percentile aggregates merge bucket-exactly. E.g.::

        run.py queryz --where kind=sample --group-by tenant \\
            --agg count --agg p99:ttft_s

    ``--json`` prints the raw payload (including the mergeable
    histogram states) for scripts."""
    import asyncio

    ap = argparse.ArgumentParser(prog="distkeras_tpu.run queryz")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8500,
                    help="a serving server's port, or a cluster router's "
                         "front port (fleet-merged result)")
    ap.add_argument("--where", action="append", default=[],
                    metavar="COL<OP>VALUE",
                    help="filter term like kind=sample or ttft_s>0.25 "
                         "(repeatable; ops = != >= <= > <)")
    ap.add_argument("--group-by", action="append", default=[],
                    metavar="COL",
                    help="group-by column, up to 2 (repeatable, or one "
                         "comma-separated list)")
    ap.add_argument("--agg", action="append", default=[], metavar="SPEC",
                    help="aggregate spec: count, sum:COL, mean:COL, or "
                         "pX:COL like p99:ttft_s (repeatable; default "
                         "count)")
    ap.add_argument("--max-groups", type=int, default=None,
                    help="distinct group keys beyond this fold into "
                         "__other__ (server default 64)")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON payload instead of the pretty table")
    args = ap.parse_args(argv)
    group_by = [c for chunk in args.group_by
                for c in chunk.split(",") if c]

    from distkeras_tpu.serving import ServingClient, ServingError
    from distkeras_tpu.serving.debugz import format_queryz

    async def go():
        async with ServingClient(args.host, args.port,
                                 max_retries=0) as client:
            return await client.queryz(
                where=args.where or None, group_by=group_by or None,
                aggs=args.agg or None, max_groups=args.max_groups)

    try:
        payload = asyncio.run(go())
    except (OSError, ConnectionError) as e:
        print(f"queryz: cannot reach {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 1
    except ServingError as e:
        print(f"queryz: server refused: {e}", file=sys.stderr)
        return 1
    print(json.dumps(payload, indent=1) if args.json
          else format_queryz(payload))
    return 0


def _write_statusz(trainer, path: str) -> bool:
    """One atomic statusz snapshot (tmp + replace, same contract as the
    weight publisher: a concurrent reader sees old or new, never torn).
    False when the trainer has no training-health layer (yet)."""
    health = getattr(trainer, "training_health", None)
    if health is None:
        return False
    import threading

    # Per-thread tmp name: the periodic writer thread and the final
    # main-thread snapshot may overlap when join() times out on a
    # wedged statusz() — two writers on ONE tmp path would interleave
    # and os.replace would publish the torn result.
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "w") as f:
            json.dump(health.statusz(), f)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def statusz_main(argv=None) -> int:
    """``statusz`` subcommand: pretty-print a training-health snapshot
    file (the JSON ``train --statusz-out`` rewrites live) — worker
    table, staleness percentiles, divergence, goodput, device memory.
    Run it in a second terminal against a live run's file."""
    ap = argparse.ArgumentParser(prog="distkeras_tpu.run statusz")
    ap.add_argument("--file", required=True,
                    help="statusz JSON written by train --statusz-out")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON payload instead of the pretty page")
    args = ap.parse_args(argv)

    from distkeras_tpu.serving.debugz import format_statusz

    try:
        with open(args.file) as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        print(f"statusz: cannot read {args.file}: {e}", file=sys.stderr)
        return 1
    print(json.dumps(payload, indent=1) if args.json
          else format_statusz(payload))
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        return serve_main(argv[1:])
    if argv and argv[0] == "cluster":
        return serve_main(argv[1:], prog="cluster", default_replicas=2)
    if argv and argv[0] == "debugz":
        return debugz_main(argv[1:])
    if argv and argv[0] == "queryz":
        return queryz_main(argv[1:])
    if argv and argv[0] == "deploy":
        return deploy_main(argv[1:])
    if argv and argv[0] == "deployz":
        return deployz_main(argv[1:])
    if argv and argv[0] == "statusz":
        return statusz_main(argv[1:])
    if argv and argv[0] == "train":  # explicit alias for the default mode
        argv = argv[1:]
    ap = argparse.ArgumentParser(prog="distkeras_tpu.run")
    ap.add_argument("--config", required=True, help="TrainerConfig JSON file")
    ap.add_argument("--data", required=True, help=".npz (features/label) or CSV")
    ap.add_argument("--model", default="mnist_mlp", help=f"one of {sorted(MODEL_ZOO)}")
    ap.add_argument("--model-args", default="{}", help="JSON kwargs for the model fn")
    ap.add_argument("--out", default=None, help="path to save trained weights")
    ap.add_argument("--metrics-out", default=None, help="JSONL per-step metrics")
    ap.add_argument("--trace-out", default=None,
                    help="enable spans; write Chrome-trace JSON (Perfetto-"
                         "loadable) of the whole run here")
    ap.add_argument("--profile-out", default=None, metavar="DIR",
                    help="capture a jax.profiler (XLA) trace of the whole "
                         "run into this directory — the device-timeline "
                         "complement to --trace-out's host spans")
    ap.add_argument("--statusz-out", default=None, metavar="PATH",
                    help="async trainers: rewrite the training-health "
                         "statusz snapshot (worker table, staleness "
                         "percentiles, divergence, goodput, device memory) "
                         "to this JSON file every --statusz-interval "
                         "seconds; inspect live with `run.py statusz "
                         "--file PATH`")
    ap.add_argument("--statusz-interval", type=float, default=10.0,
                    help="seconds between --statusz-out rewrites")
    ap.add_argument("--publish-dir", default=None, metavar="DIR",
                    help="continuous deployment: atomically publish "
                         "stamped weight files + MANIFEST.json into DIR "
                         "on the --publish-every cadence (async trainers "
                         "publish the PS center; step trainers the live "
                         "params). A `run.py deploy` controller watching "
                         "DIR canary-validates and rolls each version "
                         "through the serving fleet")
    ap.add_argument("--publish-every", default="10s", metavar="N|Ns",
                    help="publish cadence: 'Ns' = every N seconds, bare "
                         "N = every N steps (PS commits for the async "
                         "family)")
    ap.add_argument("--publish-keep", type=int, default=5,
                    help="retained published versions (older files are "
                         "pruned; the manifest's current one is always "
                         "kept)")
    ap.add_argument("--publish-min-improvement", type=float, default=None,
                    metavar="DELTA",
                    help="metric gate: only publish when the loss "
                         "improved by at least DELTA over the best "
                         "published loss (a plateaued run stops churning "
                         "the fleet)")
    ap.add_argument("--audit-recompiles", action="store_true",
                    help="count train-step compiles (+ triggering shapes); "
                         "report appears in the summary line")
    ap.add_argument("--shuffle", action="store_true")
    args = ap.parse_args(argv)

    from distkeras_tpu.telemetry import (
        MetricsRegistry,
        RecompileAuditor,
        enable_tracing,
        profile_trace,
    )
    from distkeras_tpu.tracing import MetricStream
    from distkeras_tpu.utils.config import TrainerConfig

    tracer = enable_tracing() if args.trace_out else None
    cfg = TrainerConfig.from_json(open(args.config).read())
    model = load_model(args.model, json.loads(args.model_args))
    ds = load_data(args.data, cfg.features_col, cfg.label_col)
    trainer = cfg.build(model)
    # One registry behind the whole run: step counters, PS commit/dup
    # counters, and (async trainers) the training-health histograms all
    # land in the same scrapeable surface.
    trainer.registry = MetricsRegistry()
    if args.metrics_out:
        trainer.metric_stream = MetricStream.to_jsonl(
            args.metrics_out, registry=trainer.registry)
    if args.audit_recompiles:
        trainer.auditor = RecompileAuditor(registry=trainer.registry)
    if args.publish_dir:
        from distkeras_tpu.deploy import (
            WeightPublisher, parse_publish_every,
        )

        policy = parse_publish_every(args.publish_every)
        policy.min_improvement = args.publish_min_improvement
        trainer.publisher = WeightPublisher(
            args.publish_dir, policy, keep=args.publish_keep,
            registry=trainer.registry)

    import contextlib
    import threading

    stop_statusz = threading.Event()
    statusz_thread = None
    if args.statusz_out:
        def _statusz_loop():
            while not stop_statusz.wait(args.statusz_interval):
                _write_statusz(trainer, args.statusz_out)

        statusz_thread = threading.Thread(
            target=_statusz_loop, name="statusz-writer", daemon=True)
        statusz_thread.start()

    profiler = (profile_trace(args.profile_out) if args.profile_out
                else contextlib.nullcontext())
    try:
        with profiler:
            trained = trainer.train(ds, shuffle=args.shuffle)
    finally:
        # The JSONL stream owns a file handle; the trace is only useful
        # if it lands on disk even when training dies mid-run.
        if statusz_thread is not None:
            stop_statusz.set()
            statusz_thread.join(timeout=5)
            # Final snapshot: the post-mortem view even for runs shorter
            # than one interval.
            _write_statusz(trainer, args.statusz_out)
        if trainer.metric_stream is not None:
            trainer.metric_stream.close()
        if tracer is not None:
            tracer.export_chrome_trace(args.trace_out)
    summary = {
        "trainer": cfg.trainer,
        "steps": len(trainer.get_history()),
        "training_time_s": round(trainer.get_training_time(), 3),
        "averaged_history": {
            k: round(v, 5) for k, v in trainer.get_averaged_history().items()
        },
    }
    if args.audit_recompiles:
        summary["recompile_audit"] = trainer.auditor.report()
    if args.trace_out:
        summary["trace_out"] = args.trace_out
    if args.profile_out:
        summary["profile_out"] = args.profile_out
    if args.statusz_out and getattr(trainer, "training_health", None):
        summary["statusz"] = args.statusz_out
        health = trainer.training_health
        stale = health.staleness_percentiles()
        if stale:
            summary["staleness_p99"] = round(stale["p99"], 3)
        if health.goodput_ratio is not None:
            summary["goodput_ratio"] = round(health.goodput_ratio, 6)
    if args.publish_dir and trainer.publisher is not None:
        summary["published"] = trainer.publisher.stats()
    if args.out:
        if isinstance(trained, list):  # EnsembleTrainer
            for i, t in enumerate(trained):
                t.save_weights(f"{args.out}.{i}")
            summary["saved"] = [f"{args.out}.{i}" for i in range(len(trained))]
        else:
            trained.save_weights(args.out)
            summary["saved"] = args.out
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
