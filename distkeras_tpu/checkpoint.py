"""Checkpoint / resume — closing a reference gap (SURVEY §5: dist-keras has
no checkpointing; training cannot resume mid-run).

Orbax-backed step-level save/restore of the full training position: params,
optimizer state, RNG, step counter, and — for async protocols — the PS center
and update counter, so a DynSGD run resumes with correct staleness
accounting.

Also home to the **serving weight file** helpers
(:func:`save_weights_file` / :func:`load_weights_file`): the pickle-free
serialized-pytree format ``Model.save_weights`` writes and ``run.py
serve --weights`` / the cluster's rolling ``reload`` verb read. Saves
are ATOMIC (tmp + ``os.replace``) — the reload contract is that a
replica reading the path mid-publish sees either the old file or the
new one, never a torn write. These helpers need only numpy/jax, so a
serving host without orbax installed can still hot-reload weights (the
orbax import is gated; only :class:`CheckpointManager` requires it).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
except ImportError:  # pragma: no cover - present in the dev container
    ocp = None

__all__ = ["CheckpointManager", "save_weights_file", "load_weights_file"]


def save_weights_file(path: str, variables: Any) -> str:
    """Write ``variables`` (any pytree of arrays — typically the model's
    ``{"params": ...}`` dict) to ``path`` in the serialized-pytree format,
    atomically: the bytes land in a same-directory temp file first and
    ``os.replace`` publishes them, so a concurrent reader (a replica
    executing ``reload``) can never observe a half-written file. Returns
    ``path``."""
    from distkeras_tpu.utils.pytree import pytree_to_host, serialize_pytree

    data = serialize_pytree(pytree_to_host(variables))
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # A failed publish (disk full, mid-write kill) must not litter
        # the weights directory with orphaned temp files.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_weights_file(path: str, like: Any | None = None) -> Any:
    """Read a :func:`save_weights_file` / ``Model.save_weights`` file.
    With ``like``, leaves unflatten into that exact structure; without,
    a nested dict tree is rebuilt from the recorded key paths."""
    from distkeras_tpu.utils.pytree import deserialize_pytree

    with open(path, "rb") as f:
        return deserialize_pytree(f.read(), like=like)


class CheckpointManager:
    """Thin orbax wrapper with a fixed layout:

    ``{"state": <TrainState-like pytree>, "ps": {"center":..., "num_updates":...},
    "meta": {...}}`` — any subset may be absent.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        if ocp is None:
            raise ImportError(
                "orbax-checkpoint is required for CheckpointManager "
                "(the flat-file save_weights_file/load_weights_file "
                "helpers work without it)")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(
        self,
        step: int,
        state: Any = None,
        ps_center: Any = None,
        ps_num_updates: int | None = None,
        meta: dict | None = None,
        wait: bool = True,
    ) -> None:
        items: dict[str, Any] = {}
        if state is not None:
            items["state"] = ocp.args.StandardSave(jax.device_get(state))
        if ps_center is not None:
            items["ps"] = ocp.args.StandardSave(
                {
                    "center": jax.device_get(ps_center),
                    # 0-d ndarray, not np.int64: orbax >= 0.7's standard
                    # handler rejects bare numpy SCALARS ("Unsupported
                    # type") while ndarrays round-trip fine, and int()
                    # on the restored value works for both layouts.
                    "num_updates": np.asarray(ps_num_updates or 0,
                                              np.int64),
                }
            )
        if meta:
            items["meta"] = ocp.args.JsonSave(meta)
        self._mgr.save(step, args=ocp.args.Composite(**items))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, step: int | None = None, like: Any = None) -> dict:
        """``like`` mirrors the saved layout: a dict possibly holding
        ``state`` / ``ps`` pytrees (``meta`` is restored automatically)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        saved = set(self._mgr.item_metadata(step).keys())
        items: dict[str, Any] = {}
        for key in ("state", "ps"):
            if key in saved:
                # The template passes through as-is: jax.Arrays carry their
                # shardings, so a GSPMD state restores distributed.
                template = (like or {}).get(key)
                items[key] = (
                    ocp.args.StandardRestore(template)
                    if template is not None
                    else ocp.args.StandardRestore()
                )
        if "meta" in saved:
            items["meta"] = ocp.args.JsonRestore()
        restored = self._mgr.restore(step, args=ocp.args.Composite(**items))
        return dict(restored)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()
