"""Checkpoint / resume — closing a reference gap (SURVEY §5: dist-keras has
no checkpointing; training cannot resume mid-run).

Orbax-backed step-level save/restore of the full training position: params,
optimizer state, RNG, step counter, and — for async protocols — the PS center
and update counter, so a DynSGD run resumes with correct staleness
accounting.

Also home to the **serving weight file** helpers
(:func:`save_weights_file` / :func:`load_weights_file`): the pickle-free
serialized-pytree format ``Model.save_weights`` writes and ``run.py
serve --weights`` / the cluster's rolling ``reload`` verb read. Saves
are ATOMIC (tmp + ``os.replace``) — the reload contract is that a
replica reading the path mid-publish sees either the old file or the
new one, never a torn write — and every save is **stamped with weight
provenance**: a monotonic ``version`` (prior version at the path + 1)
and a content ``digest`` (sha256 of the serialized pytree bytes,
truncated), embedded as an extra zip member the array loaders ignore.
The serving stack carries that stamp from the file into every response
and trace timeline, so a bad served answer names the exact weights that
produced it (:func:`weights_provenance` reads the stamp back; for
legacy un-stamped files it computes the SAME digest the stamper would
have, since the file bytes ARE the serialized pytree there). These
helpers need only numpy/jax, so a serving host without orbax installed
can still hot-reload weights (the orbax import is gated; only
:class:`CheckpointManager` requires it).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import time
import zipfile
from typing import Any

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
except ImportError:  # pragma: no cover - present in the dev container
    ocp = None

__all__ = [
    "CheckpointManager",
    "save_weights_file",
    "load_weights_file",
    "load_weights_file_with_provenance",
    "load_weights_meta",
    "weights_provenance",
    "weights_digest",
    "publish_weights",
    "read_manifest",
    "MANIFEST_NAME",
]

# Zip member carrying the provenance stamp. The npz readers
# (deserialize_pytree) touch only ``leaf_*`` and ``__treedef__`` members,
# so stamped files stay loadable by every existing reader — and by
# np.load directly.
_META_MEMBER = "__weights_meta__.json"


def weights_digest(data: bytes) -> str:
    """The ONE content-digest definition for weight files: sha256 over
    the serialized-pytree bytes (BEFORE the stamp member is appended),
    truncated to 16 hex chars — short enough for a log line, unique
    enough for a fleet's weight churn."""
    return hashlib.sha256(data).hexdigest()[:16]


def save_weights_file(path: str, variables: Any,
                      version: int | None = None,
                      meta: dict | None = None) -> str:
    """Write ``variables`` (any pytree of arrays — typically the model's
    ``{"params": ...}`` dict) to ``path`` in the serialized-pytree format,
    atomically: the bytes land in a same-directory temp file first and
    ``os.replace`` publishes them, so a concurrent reader (a replica
    executing ``reload``) can never observe a half-written file.

    Every save is stamped: ``version`` defaults to the previous stamped
    version at ``path`` plus one (1 for a fresh path) — monotonic per
    publish path, which is exactly the train→serve loop's cadence —
    plus the content ``digest`` and a wall-clock ``saved_at``. ``meta``
    merges extra caller fields (e.g. the trainer's step) into the stamp.
    Returns ``path``."""
    from distkeras_tpu.utils.pytree import pytree_to_host, serialize_pytree

    data = serialize_pytree(pytree_to_host(variables))
    if version is None:
        prev = load_weights_meta(path)
        version = int(prev.get("version", 0)) + 1 if prev else 1
    stamp = {
        "version": int(version),
        "digest": weights_digest(data),
        "saved_at": time.time(),
        **(meta or {}),
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        # Stamp the tmp FILE in place (zip append) rather than an
        # in-memory copy: `data` is the only full serialized copy held —
        # a multi-GB save must not transiently triple host memory.
        with open(tmp, "wb") as f:
            f.write(data)
        del data
        with zipfile.ZipFile(tmp, "a") as z:
            z.writestr(_META_MEMBER, json.dumps(stamp))
        with open(tmp, "rb") as f:
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # A failed publish (disk full, mid-write kill) must not litter
        # the weights directory with orphaned temp files.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_weights_file(path: str, like: Any | None = None) -> Any:
    """Read a :func:`save_weights_file` / ``Model.save_weights`` file.
    With ``like``, leaves unflatten into that exact structure; without,
    a nested dict tree is rebuilt from the recorded key paths."""
    from distkeras_tpu.utils.pytree import deserialize_pytree

    with open(path, "rb") as f:
        return deserialize_pytree(f.read(), like=like)


def load_weights_file_with_provenance(
        path: str, like: Any | None = None) -> tuple[Any, dict]:
    """One-read variant for reload paths: arrays AND provenance come
    from the SAME file bytes, so a concurrent atomic re-publish can
    never pair version N's arrays with version N+1's stamp."""
    from distkeras_tpu.utils.pytree import deserialize_pytree

    with open(path, "rb") as f:
        data = f.read()
    provenance = _provenance_from_bytes(data)
    provenance["path"] = os.path.abspath(path)
    return deserialize_pytree(data, like=like), provenance


def _provenance_from_bytes(data: bytes) -> dict:
    try:
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            if _META_MEMBER in z.namelist():
                meta = json.loads(z.read(_META_MEMBER).decode("utf-8"))
                if isinstance(meta, dict) and meta.get("digest"):
                    return {"version": int(meta.get("version", 0)),
                            "digest": str(meta["digest"])}
    except (ValueError, KeyError, zipfile.BadZipFile):
        pass
    return {"version": 0, "digest": weights_digest(data)}


def load_weights_meta(path: str) -> dict | None:
    """The provenance stamp of a weights file, without loading any
    arrays (a zip central-directory read). None when the file is
    missing, unreadable, or predates stamping."""
    try:
        with zipfile.ZipFile(path) as z:
            if _META_MEMBER not in z.namelist():
                return None
            meta = json.loads(z.read(_META_MEMBER).decode("utf-8"))
            return meta if isinstance(meta, dict) else None
    except (OSError, ValueError, KeyError, zipfile.BadZipFile):
        return None


def weights_provenance(path: str) -> dict:
    """``{"version": ..., "digest": ..., "path": ...}`` for a weights
    file — the stamp when present; for a legacy un-stamped file the
    digest is computed over the file bytes (identical to what the
    stamper would have recorded, since an un-stamped file IS the bare
    serialized pytree) with ``version=0``. This is what ``run.py
    serve --weights`` and the ``reload`` verb hand the engine, and what
    every response's ``weight_version`` field traces back to. Stamped
    files cost a zip central-directory read; only the legacy fallback
    (digest over the file bytes) reads the whole file."""
    meta = load_weights_meta(path)
    if meta and meta.get("digest"):
        out = {"version": int(meta.get("version", 0)),
               "digest": str(meta["digest"])}
    else:
        with open(path, "rb") as f:
            out = _provenance_from_bytes(f.read())
    out["path"] = os.path.abspath(path)
    return out


# -- publish directory: the train -> serve handoff ---------------------------
#
# A *publish directory* is the contract between a trainer and the deploy
# controller (distkeras_tpu.deploy): versioned, stamped weight files
# (``weights-v<N>.npz``, immutable once published) plus ONE atomic
# ``MANIFEST.json`` naming the newest version. Writers publish the weights
# file FIRST, then replace the manifest — a watcher that reads the
# manifest and then opens the file it names can never see a torn or
# missing publish. Old versions are retained (bounded) so a canary
# rollback or a replica restart can still load the last-good file.

MANIFEST_NAME = "MANIFEST.json"


def publish_weights(directory: str, variables: Any,
                    meta: dict | None = None, keep: int = 5) -> dict:
    """Atomically publish ``variables`` into ``directory`` and point the
    manifest at it.

    The weights land as ``weights-v<N>.npz`` (``N`` = previous manifest
    version + 1; stamped via :func:`save_weights_file`, so the file's own
    provenance agrees with the manifest), then ``MANIFEST.json`` is
    replaced (tmp + ``os.replace``) with ``{"version", "digest", "path",
    "saved_at", **meta}`` — typically ``meta={"step": ..., "loss": ...}``
    from the trainer. Returns the manifest dict (``path`` absolute).

    ``keep`` bounds retention: older ``weights-v*.npz`` files beyond the
    newest ``keep`` are deleted, except the one the manifest names (the
    invariant a deploy controller's rollback path relies on is "last-good
    still exists", which it guarantees by pinning within ``keep``).
    """
    if keep < 2:
        raise ValueError(f"keep must be >= 2 (current + last-good), "
                         f"got {keep}")
    os.makedirs(directory, exist_ok=True)
    prev = read_manifest(directory)
    version = int(prev.get("version", 0)) + 1 if prev else 1
    fname = f"weights-v{version:08d}.npz"
    path = os.path.join(directory, fname)
    save_weights_file(path, variables, version=version, meta=meta)
    manifest = {
        "version": version,
        "digest": (load_weights_meta(path) or {}).get("digest"),
        "path": fname,
        "saved_at": time.time(),
        **(meta or {}),
    }
    tmp = os.path.join(directory, f".{MANIFEST_NAME}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(directory, MANIFEST_NAME))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _prune_published(directory, keep, protect=fname)
    return {**manifest, "path": path}


def _prune_published(directory: str, keep: int, protect: str) -> None:
    """Delete all but the newest ``keep`` published versions (never the
    just-published ``protect`` file). Best-effort: a concurrent reader
    holding an old file open on a platform where unlink fails must not
    fail the publish."""
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("weights-v") and n.endswith(".npz"))
    except OSError:
        return
    for name in names[:-keep]:
        if name == protect:
            continue
        try:
            os.unlink(os.path.join(directory, name))
        except OSError:
            pass


def read_manifest(directory: str) -> dict | None:
    """The publish directory's current manifest, with ``path`` resolved
    absolute, or None when the directory has no (readable) manifest.
    Torn or garbage content returns None rather than raising — the
    watcher polls this on a cadence and an external writer's mistake
    must not kill the deploy loop."""
    try:
        with open(os.path.join(directory, MANIFEST_NAME)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(manifest, dict) or "version" not in manifest:
        return None
    path = manifest.get("path")
    if path and not os.path.isabs(path):
        manifest["path"] = os.path.join(os.path.abspath(directory), path)
    return manifest


class CheckpointManager:
    """Thin orbax wrapper with a fixed layout:

    ``{"state": <TrainState-like pytree>, "ps": {"center":..., "num_updates":...},
    "meta": {...}}`` — any subset may be absent.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        if ocp is None:
            raise ImportError(
                "orbax-checkpoint is required for CheckpointManager "
                "(the flat-file save_weights_file/load_weights_file "
                "helpers work without it)")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(
        self,
        step: int,
        state: Any = None,
        ps_center: Any = None,
        ps_num_updates: int | None = None,
        meta: dict | None = None,
        wait: bool = True,
    ) -> None:
        items: dict[str, Any] = {}
        if state is not None:
            items["state"] = ocp.args.StandardSave(jax.device_get(state))
        if ps_center is not None:
            items["ps"] = ocp.args.StandardSave(
                {
                    "center": jax.device_get(ps_center),
                    # 0-d ndarray, not np.int64: orbax >= 0.7's standard
                    # handler rejects bare numpy SCALARS ("Unsupported
                    # type") while ndarrays round-trip fine, and int()
                    # on the restored value works for both layouts.
                    "num_updates": np.asarray(ps_num_updates or 0,
                                              np.int64),
                }
            )
        if meta:
            items["meta"] = ocp.args.JsonSave(meta)
        self._mgr.save(step, args=ocp.args.Composite(**items))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, step: int | None = None, like: Any = None) -> dict:
        """``like`` mirrors the saved layout: a dict possibly holding
        ``state`` / ``ps`` pytrees (``meta`` is restored automatically)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        saved = set(self._mgr.item_metadata(step).keys())
        items: dict[str, Any] = {}
        for key in ("state", "ps"):
            if key in saved:
                # The template passes through as-is: jax.Arrays carry their
                # shardings, so a GSPMD state restores distributed.
                template = (like or {}).get(key)
                items[key] = (
                    ocp.args.StandardRestore(template)
                    if template is not None
                    else ocp.args.StandardRestore()
                )
        if "meta" in saved:
            items["meta"] = ocp.args.JsonRestore()
        restored = self._mgr.restore(step, args=ocp.args.Composite(**items))
        return dict(restored)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()
