"""Async optimization protocols as pure update rules.

The heart of dist-keras parity. The reference implements each protocol as a
(Worker subclass, ParameterServer subclass) pair exchanging pickled weights
over TCP (``distkeras/workers.py`` § ``DOWNPOURWorker``/``ADAGWorker``/
``AEASGDWorker``/``EAMSGDWorker``/``DynSGDWorker`` +
``distkeras/parameter_servers.py`` § ``DeltaParameterServer``/
``ADAGParameterServer``/``DynSGDParameterServer``). Here each protocol is a
small strategy object made of **pure PyTree functions**:

- ``server_commit(center, num_updates, payload) -> (center, num_updates)``
  — the single-owner PS state transition (no locks needed by construction);
- ``worker_begin(client, params)`` / ``worker_window(params, carry, client)``
  — the per-``communication_window`` exchange run by each worker between
  stretches of jitted local train steps.

Protocol semantics preserved from the reference:

DOWNPOUR   worker pushes the weight delta accumulated over the window, then
           pulls the fresh center; server applies ``center += delta``.
ADAG       same worker; server normalizes: ``center += delta / num_workers``
           (accumulated-gradient normalization — the reference author's own
           protocol; the 1/n scaling tames asynchronous staleness).
AEASGD     elastic averaging: worker computes the elastic force
           ``e = rho * lr * (local - center)``, applies ``local -= e`` and
           commits ``e``; server applies ``center += e``.
EAMSGD     AEASGD plus Nesterov-style momentum on the local update.
DynSGD     staleness-aware: pull returns ``(center, num_updates)``; commit
           carries the puller's ``last_update``; server applies
           ``center += delta / (staleness + 1)`` with
           ``staleness = num_updates - last_update`` and bumps the counter
           (reference ``DynSGDParameterServer.handle_commit`` semantics,
           SURVEY §3.3).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import optax

from distkeras_tpu.utils.pytree import pytree_add, pytree_scale, pytree_sub

__all__ = [
    "AsyncProtocol",
    "DOWNPOURProtocol",
    "ADAGProtocol",
    "AEASGDProtocol",
    "EAMSGDProtocol",
    "DynSGDProtocol",
]

PyTree = Any


@dataclasses.dataclass
class WorkerCarry:
    """Per-worker protocol bookkeeping between windows."""

    window_start: PyTree | None = None  # params snapshot at window start
    last_update: int = 0  # DynSGD: server counter seen at last pull


class AsyncProtocol:
    """Base strategy. Subclasses override the three hooks below."""

    name = "async"

    def __init__(self, communication_window: int = 5):
        self.communication_window = int(communication_window)

    # -- server side (runs inside the single-owner PS loop) ------------------

    def server_commit(
        self, center: PyTree, num_updates: int, payload: dict, num_workers: int
    ) -> tuple[PyTree, int]:
        raise NotImplementedError

    # -- worker side ---------------------------------------------------------

    def local_optimizer(
        self, base: optax.GradientTransformation
    ) -> optax.GradientTransformation:
        """Hook for protocols that modify the local update rule (EAMSGD)."""
        return base

    def worker_begin(self, client, params: PyTree) -> tuple[PyTree, WorkerCarry]:
        """Initial pull: start every worker from the shared center."""
        center, num_updates = client.pull()
        return center, WorkerCarry(window_start=center, last_update=num_updates)

    def worker_window(
        self, params: PyTree, carry: WorkerCarry, client
    ) -> tuple[PyTree, WorkerCarry]:
        raise NotImplementedError


class _DeltaWindowMixin:
    """Commit accumulated window delta, then pull fresh center and rebase —
    the DOWNPOUR/ADAG/DynSGD worker cadence (SURVEY §3.1 hot loop)."""

    def worker_window(self, params, carry, client):
        delta = pytree_sub(params, carry.window_start)
        client.commit({"delta": delta, "last_update": carry.last_update})
        center, num_updates = client.pull()
        return center, WorkerCarry(window_start=center, last_update=num_updates)


class DOWNPOURProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Dean et al. Downpour SGD (reference ``DOWNPOUR`` trainer +
    ``DeltaParameterServer``)."""

    name = "downpour"

    def server_commit(self, center, num_updates, payload, num_workers):
        return pytree_add(center, payload["delta"]), num_updates + 1


class ADAGProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Accumulated-gradient normalization (reference ``ADAG`` trainer +
    ``ADAGParameterServer``): commit scaled by 1/num_workers."""

    name = "adag"

    def __init__(self, communication_window: int = 12):
        super().__init__(communication_window)

    def server_commit(self, center, num_updates, payload, num_workers):
        scaled = pytree_scale(payload["delta"], 1.0 / max(1, num_workers))
        return pytree_add(center, scaled), num_updates + 1


class AEASGDProtocol(AsyncProtocol):
    """Asynchronous Elastic Averaging SGD (Zhang et al.; reference ``AEASGD``
    trainer). ``rho`` and ``learning_rate`` follow the reference kwargs."""

    name = "aeasgd"

    def __init__(
        self,
        communication_window: int = 32,
        rho: float = 5.0,
        learning_rate: float = 0.1,
    ):
        super().__init__(communication_window)
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)

    def server_commit(self, center, num_updates, payload, num_workers):
        return pytree_add(center, payload["delta"]), num_updates + 1

    def worker_window(self, params, carry, client):
        center, num_updates = client.pull()
        alpha = self.rho * self.learning_rate
        elastic = pytree_scale(pytree_sub(params, center), alpha)
        new_params = pytree_sub(params, elastic)
        client.commit({"delta": elastic, "last_update": num_updates})
        return new_params, WorkerCarry(window_start=new_params, last_update=num_updates)


class EAMSGDProtocol(AEASGDProtocol):
    """Elastic Averaging with Momentum SGD (reference ``EAMSGD`` trainer):
    AEASGD elastic exchange + Nesterov momentum on the local update."""

    name = "eamsgd"

    def __init__(
        self,
        communication_window: int = 32,
        rho: float = 5.0,
        learning_rate: float = 0.1,
        momentum: float = 0.9,
    ):
        super().__init__(communication_window, rho, learning_rate)
        self.momentum = float(momentum)

    def local_optimizer(self, base):
        return optax.chain(base, optax.trace(decay=self.momentum, nesterov=True))


class DynSGDProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Staleness-aware dynamic SGD (reference ``DynSGD`` trainer +
    ``DynSGDParameterServer``): each committed delta is damped by the
    committer's staleness. The PS update counter is load-bearing state —
    it is owned exclusively by the PS loop, making the
    read-modify-write race-free by construction (vs the reference's
    GIL-protected handler threads)."""

    name = "dynsgd"

    def server_commit(self, center, num_updates, payload, num_workers):
        staleness = max(0, num_updates - int(payload["last_update"]))
        damped = pytree_scale(payload["delta"], 1.0 / (staleness + 1))
        return pytree_add(center, damped), num_updates + 1
