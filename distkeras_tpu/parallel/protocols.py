"""Async optimization protocols as pure update rules.

The heart of dist-keras parity. The reference implements each protocol as a
(Worker subclass, ParameterServer subclass) pair exchanging pickled weights
over TCP (``distkeras/workers.py`` § ``DOWNPOURWorker``/``ADAGWorker``/
``AEASGDWorker``/``EAMSGDWorker``/``DynSGDWorker`` +
``distkeras/parameter_servers.py`` § ``DeltaParameterServer``/
``ADAGParameterServer``/``DynSGDParameterServer``). Here each protocol is a
small strategy object made of **pure PyTree functions**:

- ``server_commit(center, num_updates, payload) -> (center, num_updates)``
  — the single-owner PS state transition (no locks needed by construction);
- ``worker_begin(client, params)`` / ``worker_window(params, carry, client)``
  — the per-``communication_window`` exchange run by each worker between
  stretches of jitted local train steps.

Protocol semantics preserved from the reference:

DOWNPOUR   worker pushes the weight delta accumulated over the window, then
           pulls the fresh center; server applies ``center += delta``.
ADAG       same worker; server normalizes: ``center += delta / num_workers``
           (accumulated-gradient normalization — the reference author's own
           protocol; the 1/n scaling tames asynchronous staleness).
AEASGD     elastic averaging: worker computes the elastic force
           ``e = rho * lr * (local - center)``, applies ``local -= e`` and
           commits ``e``; server applies ``center += e``.
EAMSGD     AEASGD plus Nesterov-style momentum on the local update.
DynSGD     staleness-aware: pull returns ``(center, num_updates)``; commit
           carries the puller's ``last_update``; server applies
           ``center += delta / (staleness + 1)`` with
           ``staleness = num_updates - last_update`` and bumps the counter
           (reference ``DynSGDParameterServer.handle_commit`` semantics,
           SURVEY §3.3).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import optax

from distkeras_tpu.utils.pytree import pytree_add, pytree_scale, pytree_sub

__all__ = [
    "AsyncProtocol",
    "DOWNPOURProtocol",
    "ADAGProtocol",
    "AEASGDProtocol",
    "EAMSGDProtocol",
    "DynSGDProtocol",
]

PyTree = Any


@dataclasses.dataclass
class WorkerCarry:
    """Per-worker protocol bookkeeping between windows."""

    window_start: PyTree | None = None  # params snapshot at window start
    last_update: int = 0  # DynSGD: server counter seen at last pull


class AsyncProtocol:
    """Base strategy. Subclasses override the three hooks below."""

    name = "async"

    def __init__(self, communication_window: int = 5):
        self.communication_window = int(communication_window)

    # -- server side (runs inside the single-owner PS loop) ------------------

    def server_commit(
        self, center: PyTree, num_updates: int, payload: dict, num_workers: int
    ) -> tuple[PyTree, int]:
        raise NotImplementedError

    def server_commit_pull(
        self, center: PyTree, num_updates: int, payload: dict, num_workers: int
    ) -> tuple[PyTree, int, tuple[PyTree, int]]:
        """Fused exchange: apply the commit and produce the reply in one PS
        transition. Returns ``(new_center, new_num_updates, reply)`` where
        ``reply = (tree, counter)`` is what the committing worker receives —
        by default the fresh post-commit center, restoring the reference's
        one-round-trip-per-window cadence (``distkeras/workers.py`` §
        ``NetworkWorker`` commit+pull pair collapsed into one exchange)."""
        new_center, new_n = self.server_commit(center, num_updates, payload, num_workers)
        return new_center, new_n, (new_center, new_n)

    def server_duplicate_reply(
        self, center: PyTree, num_updates: int, payload: dict
    ) -> tuple[PyTree, int]:
        """Reply for a fused exchange whose commit was already applied (a
        retried ``commit_pull`` caught by the PS dedupe window): nothing is
        re-applied, but the worker still needs an answer."""
        return center, num_updates

    # -- worker side ---------------------------------------------------------

    def local_optimizer(
        self, base: optax.GradientTransformation
    ) -> optax.GradientTransformation:
        """Hook for protocols that modify the local update rule (EAMSGD)."""
        return base

    def worker_begin(self, client, params: PyTree) -> tuple[PyTree, WorkerCarry]:
        """Initial pull: start every worker from the shared center."""
        center, num_updates = client.pull()
        return center, WorkerCarry(window_start=center, last_update=num_updates)

    def worker_window(
        self, params: PyTree, carry: WorkerCarry, client
    ) -> tuple[PyTree, WorkerCarry]:
        raise NotImplementedError


def _device_delta(params, base):
    """Whole-tree ``params - base`` as one compiled dispatch when params
    live on device (the per-window worker delta); host numpy trees keep the
    numpy path (the PS loop must not bounce through the accelerator)."""
    import jax

    leaves = jax.tree.leaves(params)
    if leaves and isinstance(leaves[0], jax.Array):
        global _delta_jit
        if _delta_jit is None:
            _delta_jit = jax.jit(
                lambda p, b: jax.tree.map(lambda x, y: x - y, p, b)
            )
        return _delta_jit(params, base)
    return pytree_sub(params, base)


_delta_jit = None


class _DeltaWindowMixin:
    """Commit accumulated window delta and receive the fresh center in one
    fused exchange — the DOWNPOUR/ADAG/DynSGD worker cadence (SURVEY §3.1 hot
    loop) at the reference's one-RTT-per-window cost. Falls back to separate
    commit + pull round trips for clients without ``commit_pull``."""

    def worker_window(self, params, carry, client):
        delta = _device_delta(params, carry.window_start)
        payload = {"delta": delta, "last_update": carry.last_update}
        fused = getattr(client, "commit_pull", None)
        if fused is not None:
            center, num_updates = fused(payload)
        else:
            client.commit(payload)
            center, num_updates = client.pull()
        return center, WorkerCarry(window_start=center, last_update=num_updates)


class DOWNPOURProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Dean et al. Downpour SGD (reference ``DOWNPOUR`` trainer +
    ``DeltaParameterServer``)."""

    name = "downpour"

    def server_commit(self, center, num_updates, payload, num_workers):
        return pytree_add(center, payload["delta"]), num_updates + 1


class ADAGProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Accumulated-gradient normalization (reference ``ADAG`` trainer +
    ``ADAGParameterServer``): commit scaled by 1/num_workers."""

    name = "adag"

    def __init__(self, communication_window: int = 12):
        super().__init__(communication_window)

    def server_commit(self, center, num_updates, payload, num_workers):
        scaled = pytree_scale(payload["delta"], 1.0 / max(1, num_workers))
        return pytree_add(center, scaled), num_updates + 1


class AEASGDProtocol(AsyncProtocol):
    """Asynchronous Elastic Averaging SGD (Zhang et al.; reference ``AEASGD``
    trainer). ``rho`` and ``learning_rate`` follow the reference kwargs."""

    name = "aeasgd"

    def __init__(
        self,
        communication_window: int = 32,
        rho: float = 5.0,
        learning_rate: float = 0.1,
    ):
        super().__init__(communication_window)
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)

    def server_commit(self, center, num_updates, payload, num_workers):
        return pytree_add(center, payload["delta"]), num_updates + 1

    def _elastic(self, local, center):
        alpha = self.rho * self.learning_rate
        return pytree_scale(pytree_sub(local, center), alpha)

    def server_commit_pull(self, center, num_updates, payload, num_workers):
        # Fused elastic exchange: the worker ships its *local* params; the
        # PS computes the elastic force against the center it owns, applies
        # ``center += e``, and replies with ``e`` so the worker applies
        # ``local -= e``. Exactly the reference's pull→compute→commit
        # semantics (``distkeras/workers.py`` § ``AEASGDWorker``) collapsed
        # into one round trip, with both sides using the identical force.
        if "local" in payload:
            e = self._elastic(payload["local"], center)
            return pytree_add(center, e), num_updates + 1, (e, num_updates)
        new_center, new_n = self.server_commit(center, num_updates, payload, num_workers)
        return new_center, new_n, (new_center, new_n)

    def server_duplicate_reply(self, center, num_updates, payload):
        # The original reply was lost in transit after the commit applied;
        # recompute the force against the (post-apply) center without
        # re-applying it.
        if "local" in payload:
            return self._elastic(payload["local"], center), num_updates
        return center, num_updates

    def worker_window(self, params, carry, client):
        fused = getattr(client, "commit_pull", None)
        if fused is not None:
            e, num_updates = fused({"local": params, "last_update": carry.last_update})
            new_params = pytree_sub(params, e)
            return new_params, WorkerCarry(
                window_start=new_params, last_update=num_updates
            )
        center, num_updates = client.pull()
        elastic = self._elastic(params, center)
        new_params = pytree_sub(params, elastic)
        client.commit({"delta": elastic, "last_update": num_updates})
        return new_params, WorkerCarry(window_start=new_params, last_update=num_updates)


class EAMSGDProtocol(AEASGDProtocol):
    """Elastic Averaging with Momentum SGD (reference ``EAMSGD`` trainer):
    AEASGD elastic exchange + Nesterov momentum on the local update."""

    name = "eamsgd"

    def __init__(
        self,
        communication_window: int = 32,
        rho: float = 5.0,
        learning_rate: float = 0.1,
        momentum: float = 0.9,
    ):
        super().__init__(communication_window, rho, learning_rate)
        self.momentum = float(momentum)

    def local_optimizer(self, base):
        return optax.chain(base, optax.trace(decay=self.momentum, nesterov=True))


class DynSGDProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Staleness-aware dynamic SGD (reference ``DynSGD`` trainer +
    ``DynSGDParameterServer``): each committed delta is damped by the
    committer's staleness. The PS update counter is load-bearing state —
    it is owned exclusively by the PS loop, making the
    read-modify-write race-free by construction (vs the reference's
    GIL-protected handler threads)."""

    name = "dynsgd"

    def server_commit(self, center, num_updates, payload, num_workers):
        staleness = max(0, num_updates - int(payload["last_update"]))
        damped = pytree_scale(payload["delta"], 1.0 / (staleness + 1))
        return pytree_add(center, damped), num_updates + 1
