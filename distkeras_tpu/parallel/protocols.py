"""Async optimization protocols as pure update rules.

The heart of dist-keras parity. The reference implements each protocol as a
(Worker subclass, ParameterServer subclass) pair exchanging pickled weights
over TCP (``distkeras/workers.py`` § ``DOWNPOURWorker``/``ADAGWorker``/
``AEASGDWorker``/``EAMSGDWorker``/``DynSGDWorker`` +
``distkeras/parameter_servers.py`` § ``DeltaParameterServer``/
``ADAGParameterServer``/``DynSGDParameterServer``). Here each protocol is a
small strategy object made of **pure PyTree functions**:

- ``server_commit(center, num_updates, payload) -> (center, num_updates)``
  — the single-owner PS state transition (no locks needed by construction);
- ``worker_begin(client, params)`` / ``worker_window(params, carry, client)``
  — the per-``communication_window`` exchange run by each worker between
  stretches of jitted local train steps.

Protocol semantics preserved from the reference:

DOWNPOUR   worker pushes the weight delta accumulated over the window, then
           pulls the fresh center; server applies ``center += delta``.
ADAG       same worker; server normalizes: ``center += delta / num_workers``
           (accumulated-gradient normalization — the reference author's own
           protocol; the 1/n scaling tames asynchronous staleness).
AEASGD     elastic averaging: worker computes the elastic force
           ``e = rho * lr * (local - center)``, applies ``local -= e`` and
           commits ``e``; server applies ``center += e``.
EAMSGD     AEASGD plus Nesterov-style momentum on the local update.
DynSGD     staleness-aware: pull returns ``(center, num_updates)``; commit
           carries the puller's ``last_update``; server applies
           ``center += delta / (staleness + 1)`` with
           ``staleness = num_updates - last_update`` and bumps the counter
           (reference ``DynSGDParameterServer.handle_commit`` semantics,
           SURVEY §3.3).
"""

from __future__ import annotations

import collections
import dataclasses
import uuid
from typing import Any

import jax
import ml_dtypes
import numpy as np
import optax

from distkeras_tpu.utils.pytree import (
    pytree_add,
    pytree_l2,
    pytree_scale,
    pytree_sub,
    pytree_to_host,
)

__all__ = [
    "AsyncProtocol",
    "DOWNPOURProtocol",
    "ADAGProtocol",
    "AEASGDProtocol",
    "EAMSGDProtocol",
    "DynSGDProtocol",
]

PyTree = Any

# High bit of the fused-exchange reply counter: "the PS lost your mirror —
# re-bootstrap with full params" (fits the wire's u64 counter field).
_REBOOTSTRAP = 1 << 63


@dataclasses.dataclass
class WorkerCarry:
    """Per-worker protocol bookkeeping between windows."""

    window_start: PyTree | None = None  # params snapshot at window start
    last_update: int = 0  # DynSGD: server counter seen at last pull
    worker_id: str = ""  # elastic family: keys the server-side mirror
    mirror: PyTree | None = None  # elastic family: shared worker/PS mirror


class AsyncProtocol:
    """Base strategy. Subclasses override the three hooks below."""

    name = "async"

    def __init__(self, communication_window: int = 5):
        self.communication_window = int(communication_window)

    # -- server side (runs inside the single-owner PS loop) ------------------

    def server_commit(
        self, center: PyTree, num_updates: int, payload: dict, num_workers: int
    ) -> tuple[PyTree, int]:
        raise NotImplementedError

    def server_commit_pull(
        self, center: PyTree, num_updates: int, payload: dict, num_workers: int
    ) -> tuple[PyTree, int, tuple[PyTree, int]]:
        """Fused exchange: apply the commit and produce the reply in one PS
        transition. Returns ``(new_center, new_num_updates, reply)`` where
        ``reply = (tree, counter)`` is what the committing worker receives —
        by default the fresh post-commit center, restoring the reference's
        one-round-trip-per-window cadence (``distkeras/workers.py`` §
        ``NetworkWorker`` commit+pull pair collapsed into one exchange)."""
        new_center, new_n = self.server_commit(center, num_updates, payload, num_workers)
        return new_center, new_n, (new_center, new_n)

    def server_duplicate_reply(
        self, center: PyTree, num_updates: int, payload: dict
    ) -> tuple[PyTree, int]:
        """Reply for a fused exchange whose commit was already applied (a
        retried ``commit_pull`` caught by the PS dedupe window): nothing is
        re-applied, but the worker still needs an answer."""
        return center, num_updates

    # -- health telemetry ----------------------------------------------------

    def commit_stats(
        self, center: PyTree, num_updates: int, payload: dict,
        num_workers: int
    ) -> dict:
        """Health accounting for ONE commit, evaluated against the
        PRE-commit PS state (the staleness and divergence definitions
        need the counter/center the committer raced against). Called by
        the PS loop when a :class:`~distkeras_tpu.telemetry.
        training_health.TrainingHealth` is attached; one O(n_params)
        host pass, same order as the commit apply itself. Returns:

        - ``staleness`` — ``num_updates - last_update`` (the quantity
          DynSGD damps by; 0 for a perfectly fresh pull);
        - ``damping`` — the scalar mass factor this protocol applies to
          the update (goodput = damped / committed mass);
        - ``update_norm`` — L2 of the committed update, when the
          payload carries one;
        - ``divergence`` — elastic family only: ``||local - center||``.
        """
        out: dict = {"damping": 1.0}
        last = payload.get("last_update")
        if last is not None:
            out["staleness"] = max(0, num_updates - int(last))
        if "delta" in payload:
            out["update_norm"] = pytree_l2(payload["delta"])
        return out

    # -- worker side ---------------------------------------------------------

    def local_optimizer(
        self, base: optax.GradientTransformation
    ) -> optax.GradientTransformation:
        """Hook for protocols that modify the local update rule (EAMSGD)."""
        return base

    def worker_begin(self, client, params: PyTree) -> tuple[PyTree, WorkerCarry]:
        """Initial pull: start every worker from the shared center."""
        center, num_updates = client.pull()
        return center, WorkerCarry(window_start=center, last_update=num_updates)

    def worker_window(
        self, params: PyTree, carry: WorkerCarry, client
    ) -> tuple[PyTree, WorkerCarry]:
        raise NotImplementedError


def _device_delta(params, base):
    """Whole-tree ``params - base`` as one compiled dispatch when params
    live on device (the per-window worker delta); host numpy trees keep the
    numpy path (the PS loop must not bounce through the accelerator)."""
    leaves = jax.tree.leaves(params)
    if leaves and isinstance(leaves[0], jax.Array):
        global _delta_jit
        if _delta_jit is None:
            _delta_jit = jax.jit(
                lambda p, b: jax.tree.map(lambda x, y: x - y, p, b)
            )
        return _delta_jit(params, base)
    return pytree_sub(params, base)


_delta_jit = None


def _wire_bf16(tree):
    """Cast wide float leaves to bfloat16 for the wire (half of f32 bytes);
    everything else ships unchanged. Exact for trees already in bf16.
    Host-side ml_dtypes cast (round-to-nearest-even, same as XLA) — the PS
    loop must never bounce trees through a device (ps.py design note)."""

    def cast(x):
        a = np.asarray(x)
        if a.dtype.kind == "f" and a.dtype.itemsize > 2:
            return a.astype(ml_dtypes.bfloat16)
        return a

    return jax.tree.map(cast, tree)


def _wire_f32(tree):
    """Upcast bf16 wire leaves back to float32 (exact — bf16 is a prefix of
    f32); other leaves pass through."""

    def up(x):
        a = np.asarray(x)
        if a.dtype.name == "bfloat16":
            return a.astype(np.float32)
        return a

    return jax.tree.map(up, tree)


class _DeltaWindowMixin:
    """Commit accumulated window delta and receive the fresh center in one
    fused exchange — the DOWNPOUR/ADAG/DynSGD worker cadence (SURVEY §3.1 hot
    loop) at the reference's one-RTT-per-window cost. Falls back to separate
    commit + pull round trips for clients without ``commit_pull``."""

    def worker_window(self, params, carry, client):
        delta = _device_delta(params, carry.window_start)
        payload = {"delta": delta, "last_update": carry.last_update}
        fused = getattr(client, "commit_pull", None)
        if fused is not None:
            center, num_updates = fused(payload)
        else:
            client.commit(payload)
            center, num_updates = client.pull()
        return center, WorkerCarry(window_start=center, last_update=num_updates)


class DOWNPOURProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Dean et al. Downpour SGD (reference ``DOWNPOUR`` trainer +
    ``DeltaParameterServer``)."""

    name = "downpour"

    def server_commit(self, center, num_updates, payload, num_workers):
        return pytree_add(center, payload["delta"]), num_updates + 1


class ADAGProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Accumulated-gradient normalization (reference ``ADAG`` trainer +
    ``ADAGParameterServer``): commit scaled by 1/num_workers."""

    name = "adag"

    def __init__(self, communication_window: int = 12):
        super().__init__(communication_window)

    def server_commit(self, center, num_updates, payload, num_workers):
        scaled = pytree_scale(payload["delta"], 1.0 / max(1, num_workers))
        return pytree_add(center, scaled), num_updates + 1

    def commit_stats(self, center, num_updates, payload, num_workers):
        out = super().commit_stats(center, num_updates, payload, num_workers)
        out["damping"] = 1.0 / max(1, num_workers)
        return out


class AEASGDProtocol(AsyncProtocol):
    """Asynchronous Elastic Averaging SGD (Zhang et al.; reference ``AEASGD``
    trainer). ``rho`` and ``learning_rate`` follow the reference kwargs.

    Wire format of the fused exchange (one RTT per window, like the
    reference's pull→compute→commit pair): the first window of each worker
    bootstraps by shipping its full-precision ``local`` params; every later
    window ships only ``bf16(local - mirror)``, where ``mirror`` is a
    per-worker tree maintained **bit-identically** on both sides (both
    advance it as ``mirror + f32(diff) - f32(e)`` from the very bytes that
    crossed the wire). The PS reconstructs ``local ≈ mirror + diff``,
    computes the elastic force against the center *it* owns, applies
    ``center += e``, and replies ``bf16(e)``. Steady-state wire cost is
    2 bytes/param each way vs 4+4 for raw f32 — a 2× reduction — and the
    bf16 rounding only ever touches *differences* of nearby trees (the
    window's local progress, and the force ``α·(local - center)``), never
    absolute weights, so the truncation is benign the same way bf16 commit
    deltas are (see :class:`distkeras_tpu.parallel.ha.CompressingClient`).
    PS-side cost: up to ``max(2*num_workers, 4)`` mirror trees (stored in
    ``mirror_dtype``, default bf16 — the mirror's own rounding cancels out
    of the reconstruction, see ``_round_mirror``) plus up to
    ``max(4*num_workers, 8)`` recorded replies (f32 model-sized worst case
    after a bootstrap exchange, bf16 force-sized in steady state) —
    worst-case budget ``num_workers * (2*2 + 4*4) = 20 bytes/param``
    (:meth:`host_state_budget`, logged at service start and asserted in
    ``tests/test_protocols.py``).
    """

    name = "aeasgd"

    def __init__(
        self,
        communication_window: int = 32,
        rho: float = 5.0,
        learning_rate: float = 0.1,
        mirror_dtype: str = "bfloat16",
    ):
        super().__init__(communication_window)
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)
        # Mirror storage precision. The wire is already bf16-rounded both
        # directions, and the mirror's own rounding cancels out of the
        # reconstruction (local_est - local = bf16(δ) - δ regardless of the
        # mirror's absolute error), so bf16 halves the PS's dominant host
        # cost. Accuracy note: δ = local - mirror now carries the mirror's
        # PARAMETER-scale bf16 residual, so the per-window reconstruction
        # error grows by roughly |param|·2^-18 on top of the |update|·2^-9
        # wire rounding a float32 mirror already had — benign for elastic
        # averaging, but not free. Both sides round with the SAME
        # round-to-nearest-even cast in the same expression order, keeping
        # the mirrors bit-identical. "float32" restores the old behavior.
        if mirror_dtype not in ("bfloat16", "float32"):
            raise ValueError(f"mirror_dtype must be bfloat16|float32, got {mirror_dtype!r}")
        self.mirror_dtype = mirror_dtype
        # Server-side per-worker state, touched only by the single-owner PS
        # loop: the shared mirror tree and the last fused reply (replayed
        # verbatim for a deduped retry — exactly-once answers). Each is
        # LRU-bounded INDEPENDENTLY (see _set_mirror/_set_reply): worker ids
        # are per-incarnation, so restarts would otherwise leak a
        # model-sized tree each. Evicting a live worker's mirror is safe —
        # it just re-bootstraps next window — but its reply must outlive the
        # mirror: if the reply died with the mirror, a lost-reply retry
        # arriving after eviction would be told "nothing applied" when the
        # commit DID move the center, and the worker would skip its side of
        # the elastic pull (asymmetric apply). A reply is superseded by the
        # worker's next successful exchange; only 2×num_workers dead
        # incarnations can age one out, so the asymmetric window survives
        # only a PS restart (documented as accepted elastic-averaging noise
        # — the next bootstrap re-centers the pair).
        self._mirrors: "collections.OrderedDict[str, PyTree]" = (
            collections.OrderedDict()
        )
        self._last_reply: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict()
        )
        # Consume-once memo handing commit_stats' local-params
        # reconstruction to the server_commit_pull that immediately
        # follows it in the single-owner PS loop (see _local_of).
        self._local_memo: tuple | None = None

    def server_commit(self, center, num_updates, payload, num_workers):
        return pytree_add(center, payload["delta"]), num_updates + 1

    def _elastic(self, local, center):
        alpha = self.rho * self.learning_rate
        return pytree_scale(pytree_sub(local, center), alpha)

    def _round_mirror(self, tree):
        """Round a freshly-advanced mirror to the storage dtype — the ONE
        cast both sides share; any asymmetry here would split the mirrors."""
        return _wire_bf16(tree) if self.mirror_dtype == "bfloat16" else tree

    def _local_of(self, payload):
        """Reconstruct the committing worker's local params (bootstrap
        ``local``, or steady-state mirror + ``elastic_diff``); None when
        the mirror is gone and the diff alone cannot. One O(n_params)
        host pass, shared between commit_stats and the
        server_commit_pull that immediately follows it in the
        single-owner PS loop via a consume-once memo — health telemetry
        must not double the loop's dominant host cost."""
        memo, self._local_memo = self._local_memo, None
        if memo is not None and memo[0] is payload:
            return memo[1]
        if "elastic_diff" in payload:
            wid = payload.get("worker_id")
            if wid not in self._mirrors:
                return None
            return pytree_add(
                _wire_f32(self._mirrors[wid]),
                _wire_f32(payload["elastic_diff"]))
        if "local" in payload:
            return pytree_to_host(payload["local"])
        return None

    def commit_stats(self, center, num_updates, payload, num_workers):
        """Elastic health: ``divergence = ||local - center||_2`` against
        the pre-commit center (the quantity elastic averaging is built
        to shrink — its growth IS the diverging-run signal), and the
        applied force's norm ``alpha * divergence`` as the update mass.
        The local-params reconstruction is memoized for the
        server_commit_pull about to apply this same payload."""
        out = super().commit_stats(center, num_updates, payload, num_workers)
        local = self._local_of(payload)
        if local is not None:
            self._local_memo = (payload, local)
            divergence = pytree_l2(pytree_sub(local, center))
            out["divergence"] = divergence
            out["update_norm"] = self.rho * self.learning_rate * divergence
        return out

    def host_state_budget(self, n_params: int, num_workers: int) -> int:
        """Worst-case PS host bytes for this protocol's per-worker state:
        ``max(2N, 4)`` mirrors (mirror_dtype) + ``max(4N, 8)`` recorded
        replies (f32 model-sized worst case — a bootstrap reply; steady
        state is bf16 force-sized). Logged at service start."""
        mirror_bytes = 2 if self.mirror_dtype == "bfloat16" else 4
        mirrors = max(2 * int(num_workers), 4) * mirror_bytes * n_params
        replies = max(4 * int(num_workers), 8) * 4 * n_params
        return mirrors + replies

    def server_commit_pull(self, center, num_updates, payload, num_workers):
        # Fused elastic exchange (see class docstring). Two request shapes:
        # bootstrap ``local`` (full precision) and steady-state
        # ``elastic_diff`` (bf16 delta against the shared mirror).
        wid = payload.get("worker_id")
        if "elastic_diff" in payload:
            local_est = self._local_of(payload)
            if local_est is None:
                # Mirror lost (PS restarted from checkpoint, or LRU-evicted):
                # the diff alone cannot reconstruct the worker's local
                # params. Apply nothing; the flagged counter tells the
                # worker to re-bootstrap with full params next window.
                # Nothing is recorded: a deduped retry reconstructs the same
                # flagged zero reply from its own payload in
                # server_duplicate_reply (storing it here would leak a
                # model-sized tree per dead incarnation — the wid is not in
                # _mirrors, so _set_mirror's eviction can never reach it).
                zero = pytree_scale(payload["elastic_diff"], 0.0)  # stays bf16: unread
                return center, num_updates, (zero, _REBOOTSTRAP | num_updates)
            e_wire = _wire_bf16(self._elastic(local_est, center))
            e = _wire_f32(e_wire)
            self._set_mirror(
                wid, self._round_mirror(pytree_sub(local_est, e)), num_workers
            )
            reply = (e_wire, num_updates)
            self._set_reply(wid, reply, num_workers)
            return pytree_add(center, e), num_updates + 1, reply
        if "local" in payload:
            local = self._local_of(payload)
            e = self._elastic(local, center)
            reply = (e, num_updates)
            if wid is not None:
                self._set_mirror(
                    wid, self._round_mirror(pytree_sub(local, e)), num_workers
                )
                self._set_reply(wid, reply, num_workers)
            return pytree_add(center, e), num_updates + 1, reply
        new_center, new_n = self.server_commit(center, num_updates, payload, num_workers)
        return new_center, new_n, (new_center, new_n)

    def _set_mirror(self, wid, mirror, num_workers):
        """Store a worker's mirror, LRU-evicting stale incarnations beyond
        2×num_workers (each mirror is a full model copy; worker ids are
        per-incarnation uuids, so churn would otherwise grow this without
        bound). An evicted live worker just re-bootstraps next window.
        Replies are NOT evicted here — they carry the exactly-once
        guarantee past a mirror eviction (see __init__) and age out of
        their own LRU in _set_reply."""
        self._mirrors[wid] = mirror
        self._mirrors.move_to_end(wid)
        bound = max(2 * int(num_workers), 4)
        while len(self._mirrors) > bound:
            self._mirrors.popitem(last=False)

    def _set_reply(self, wid, reply, num_workers):
        """Record the fused reply for dedupe replay, LRU-bounded on its own
        clock at TWICE the mirror bound: a reply outlives its mirror by a
        full extra churn cycle, every dedupe replay refreshes its recency
        (an actively-retrying worker keeps its answer alive indefinitely),
        and a worker's next successful exchange supersedes it. The
        asymmetric-apply window therefore needs a lost reply AND
        4×num_workers other exchanges before the retry AND no replay
        refresh in between — or a PS restart — and is accepted as elastic
        noise (self-healing at the next bootstrap)."""
        self._last_reply[wid] = reply
        self._last_reply.move_to_end(wid)
        bound = max(4 * int(num_workers), 8)
        while len(self._last_reply) > bound:
            self._last_reply.popitem(last=False)

    def server_duplicate_reply(self, center, num_updates, payload):
        # The original reply was lost in transit after the commit applied;
        # replay the recorded answer (the mirror has already advanced, so
        # recomputing the force would double-count the diff).
        wid = payload.get("worker_id")
        if wid in self._last_reply and ("local" in payload or "elastic_diff" in payload):
            self._last_reply.move_to_end(wid)  # a retry storm keeps it pinned
            return self._last_reply[wid]
        if "local" in payload:
            return self._elastic(pytree_to_host(payload["local"]), center), num_updates
        if "elastic_diff" in payload:
            # No recorded reply (evicted, or PS restarted between the
            # original and the retry): never hand back the raw center — the
            # worker would subtract it as if it were the force. Flag a
            # re-bootstrap instead.
            zero = pytree_scale(payload["elastic_diff"], 0.0)  # stays bf16: unread
            return zero, _REBOOTSTRAP | num_updates
        return center, num_updates

    def worker_window(self, params, carry, client):
        fused = getattr(client, "commit_pull", None)
        if fused is not None and getattr(client, "wire_is_local", False):
            # In-process transport: bytes are free and replies cannot be
            # lost, so the delta-mirror machinery (bf16 casts + mirror
            # advance on BOTH sides, dedupe replay state) is pure host CPU
            # with nothing to buy — measured 1.52x-vs-sync steady state
            # against ADAG's 1.1-1.27x on loopback (BASELINE.md round 5).
            # Ship the full-precision local tree with no worker_id; the PS
            # computes and applies the force and skips all per-worker
            # bookkeeping (`if wid is not None` in server_commit_pull).
            local = pytree_to_host(params)
            e, num_updates = fused(
                {"local": local, "last_update": carry.last_update}
            )
            new_params = pytree_sub(params, _wire_f32(e))
            return new_params, WorkerCarry(
                window_start=new_params, last_update=num_updates
            )
        if fused is not None:
            wid = carry.worker_id or uuid.uuid4().hex
            local = pytree_to_host(params)
            if carry.mirror is None:
                # Bootstrap window: full-precision local; both sides then
                # hold the identical mirror ``local - e``.
                e, num_updates = fused(
                    {"local": local, "worker_id": wid,
                     "last_update": carry.last_update}
                )
                e = _wire_f32(e)
                mirror = self._round_mirror(pytree_sub(local, e))
            else:
                diff_wire = _wire_bf16(
                    pytree_sub(local, _wire_f32(carry.mirror))
                )
                e_wire, num_updates = fused(
                    {"elastic_diff": diff_wire, "worker_id": wid,
                     "last_update": carry.last_update}
                )
                if num_updates & _REBOOTSTRAP:
                    # PS lost the mirror; nothing was applied. Skip this
                    # window's exchange and re-bootstrap on the next one.
                    return params, WorkerCarry(
                        window_start=params,
                        last_update=num_updates & ~_REBOOTSTRAP,
                        worker_id=wid, mirror=None,
                    )
                e = _wire_f32(e_wire)
                # Advance the shared mirror from the wire bytes — the same
                # arithmetic, in the same order, and the same storage
                # rounding as the PS.
                mirror = self._round_mirror(
                    pytree_sub(
                        pytree_add(_wire_f32(carry.mirror), _wire_f32(diff_wire)),
                        e,
                    )
                )
            new_params = pytree_sub(params, e)
            return new_params, WorkerCarry(
                window_start=new_params, last_update=num_updates,
                worker_id=wid, mirror=mirror,
            )
        center, num_updates = client.pull()
        elastic = self._elastic(params, center)
        new_params = pytree_sub(params, elastic)
        client.commit({"delta": elastic, "last_update": num_updates})
        return new_params, WorkerCarry(window_start=new_params, last_update=num_updates)


class EAMSGDProtocol(AEASGDProtocol):
    """Elastic Averaging with Momentum SGD (reference ``EAMSGD`` trainer):
    AEASGD elastic exchange + Nesterov momentum on the local update."""

    name = "eamsgd"

    def __init__(
        self,
        communication_window: int = 32,
        rho: float = 5.0,
        learning_rate: float = 0.1,
        momentum: float = 0.9,
    ):
        super().__init__(communication_window, rho, learning_rate)
        self.momentum = float(momentum)

    def local_optimizer(self, base):
        return optax.chain(base, optax.trace(decay=self.momentum, nesterov=True))


class DynSGDProtocol(_DeltaWindowMixin, AsyncProtocol):
    """Staleness-aware dynamic SGD (reference ``DynSGD`` trainer +
    ``DynSGDParameterServer``): each committed delta is damped by the
    committer's staleness. The PS update counter is load-bearing state —
    it is owned exclusively by the PS loop, making the
    read-modify-write race-free by construction (vs the reference's
    GIL-protected handler threads)."""

    name = "dynsgd"

    def server_commit(self, center, num_updates, payload, num_workers):
        staleness = max(0, num_updates - int(payload["last_update"]))
        damped = pytree_scale(payload["delta"], 1.0 / (staleness + 1))
        return pytree_add(center, damped), num_updates + 1

    def commit_stats(self, center, num_updates, payload, num_workers):
        # The SAME damping expression server_commit applies — goodput
        # accounting must never disagree with the update rule.
        out = super().commit_stats(center, num_updates, payload, num_workers)
        staleness = out.get("staleness", 0)
        out["damping"] = 1.0 / (staleness + 1)
        return out
