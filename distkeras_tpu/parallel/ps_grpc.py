"""Cross-host parameter-server transport over gRPC (DCN plane).

The reference's wire layer is ``distkeras/networking.py``: raw TCP sockets
carrying pickled, length-prefixed weight dicts to a driver-side PS thread,
plus ``determine_host_address()`` for discovery. This module is its
TPU-cluster equivalent:

- frames are the pickle-free npz PyTree encoding
  (:func:`distkeras_tpu.utils.pytree.serialize_pytree`) — safe to accept
  from the network, unlike pickle;
- the server is a thin gRPC front that forwards pull/commit messages into
  the same single-owner :class:`ParameterServerService` loop used
  in-process, so protocol semantics (incl. DynSGD's update counter) are
  identical regardless of transport;
- async-protocol traffic rides DCN between worker islands while each
  island's sync all-reduce rides ICI — the two-plane design from SURVEY §5.

grpcio is used without generated stubs (GenericRpcHandler + raw method
handlers) so no protoc step is needed at build or run time.
"""

from __future__ import annotations

import socket
import struct
from concurrent import futures
from typing import Any

import numpy as np

from distkeras_tpu.parallel.ps import ParameterServerService
from distkeras_tpu.utils.pytree import deserialize_pytree, serialize_pytree

__all__ = [
    "determine_host_address",
    "GrpcParameterServer",
    "GrpcClient",
    "DEFAULT_PORT",
]

DEFAULT_PORT = 50515
_SERVICE = "distkeras_tpu.ParameterServer"


def determine_host_address() -> str:
    """Best-effort routable address of this host (reference
    ``distkeras/networking.py`` § ``determine_host_address``)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # No packets are sent; connect() on UDP just resolves the route.
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


# -- wire format -------------------------------------------------------------
# pull request:        b""            -> reply: u64 num_updates | npz(center)
# commit request:      u64 last_update | npz(delta)  -> reply: b"\x01"
# commit_pull request: same frame as commit -> reply: same frame as pull reply
#
# The commit tree may be wrapped in a dict carrying out-of-band markers as
# extra npz leaves: "__commit_id__" (dedupe stamp), "__local__" (the tree is
# the worker's local params for a fused elastic exchange, not a delta),
# "__elastic_diff__" (the tree is a bf16 delta against the worker's shared
# mirror — AEASGD steady-state), and "__worker_id__" (keys the PS-side
# mirror for the elastic family).


def _encode_pull_reply(center: Any, num_updates: int) -> bytes:
    return struct.pack("<Q", num_updates) + serialize_pytree(center)


def _decode_pull_reply(data: bytes, like: Any = None) -> tuple[Any, int]:
    (num_updates,) = struct.unpack("<Q", data[:8])
    return deserialize_pytree(data[8:], like=like), num_updates


def _encode_commit(payload: dict) -> bytes:
    """Build the commit wire frame from a client payload dict
    (keys: delta|local, optional commit_id, last_update)."""
    import jax

    key = (
        "local" if "local" in payload
        else "elastic_diff" if "elastic_diff" in payload
        else "delta"
    )
    tree = jax.tree.map(np.asarray, payload[key])
    markers = {}
    if "commit_id" in payload:
        markers["__commit_id__"] = _id_to_array(payload["commit_id"])
    if "worker_id" in payload:
        markers["__worker_id__"] = _id_to_array(payload["worker_id"])
    if key == "local":
        markers["__local__"] = np.ones((1,), np.uint8)
    elif key == "elastic_diff":
        markers["__elastic_diff__"] = np.ones((1,), np.uint8)
    if markers:
        tree = {"d": tree, **markers}
    return struct.pack("<Q", int(payload.get("last_update", 0))) + serialize_pytree(
        tree
    )


def _decode_commit(data: bytes) -> dict:
    (last_update,) = struct.unpack("<Q", data[:8])
    tree = deserialize_pytree(data[8:])
    out = {"last_update": int(last_update)}
    key = "delta"
    _markers = ("__commit_id__", "__local__", "__elastic_diff__", "__worker_id__")
    if isinstance(tree, dict) and any(m in tree for m in _markers):
        if "__commit_id__" in tree:
            out["commit_id"] = _array_to_id(tree["__commit_id__"])
        if "__worker_id__" in tree:
            out["worker_id"] = _array_to_id(tree["__worker_id__"])
        if "__local__" in tree:
            key = "local"
        elif "__elastic_diff__" in tree:
            key = "elastic_diff"
        tree = tree["d"]
    out[key] = tree
    return out


class GrpcParameterServer:
    """gRPC front-end around a :class:`ParameterServerService`.

    Lifecycle mirrors the reference PS (``initialize``/``run``/``stop``):

        ps = GrpcParameterServer(protocol, center, num_workers, port=0)
        port = ps.start()          # also starts the single-owner loop
        ...
        final = ps.get_model(); ps.stop()
    """

    def __init__(
        self,
        protocol,
        center,
        num_workers,
        host: str = "127.0.0.1",
        port: int = DEFAULT_PORT,
        max_message_mb: int = 1024,
        registry=None,
        health=None,
    ):
        """``host`` defaults to loopback: the PS speaks an unauthenticated
        protocol, so exposing it beyond the host must be an explicit choice
        (``host="0.0.0.0"``) made only on an isolated/trusted network — an
        open PS port lets anyone pull weights or poison training with
        arbitrary deltas. ``max_message_mb`` bounds frame size (commit frames
        scale with model size; 1 GiB covers multi-hundred-M-param models
        while still rejecting pathological frames). ``registry``/``health``
        thread straight through to the wrapped
        :class:`ParameterServerService` — the gRPC front end adds no
        telemetry of its own, so a remote fleet's commit staleness lands
        in the same statusz a local one's does."""
        import grpc

        self._grpc = grpc
        self.service = ParameterServerService(
            protocol, center, num_workers, registry=registry, health=health)
        self._host = host
        self._port = port
        self._max_message_bytes = int(max_message_mb) * 1024 * 1024
        self._server = None

    def _handle(self, method: str):
        grpc = self._grpc
        inproc = self.service.client()

        def pull(request: bytes, context) -> bytes:
            center, num_updates = inproc.pull()
            return _encode_pull_reply(center, num_updates)

        def commit(request: bytes, context) -> bytes:
            inproc.commit(_decode_commit(request))
            return b"\x01"

        def commit_pull(request: bytes, context) -> bytes:
            tree, num_updates = inproc.commit_pull(_decode_commit(request))
            return _encode_pull_reply(tree, num_updates)

        def health(request: bytes, context) -> bytes:
            import json

            return json.dumps(self.service.health()).encode()

        fn = {
            "pull": pull,
            "commit": commit,
            "commit_pull": commit_pull,
            "health": health,
        }.get(method)
        if fn is None:
            return None
        return grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )

    def start(self) -> int:
        grpc = self._grpc
        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                name = handler_call_details.method.rsplit("/", 1)[-1]
                return outer._handle(name)

        self.service.start()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8),
            options=[
                ("grpc.max_receive_message_length", self._max_message_bytes),
                ("grpc.max_send_message_length", self._max_message_bytes),
            ],
        )
        self._server.add_generic_rpc_handlers((Handler(),))
        self._port = self._server.add_insecure_port(f"{self._host}:{self._port}")
        self._server.start()
        return self._port

    @property
    def port(self) -> int:
        return self._port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1).wait()
            self._server = None
        self.service.stop()

    def get_model(self):
        return self.service.get_model()


class GrpcClient:
    """Worker-side client with the same ``pull``/``commit`` surface as
    :class:`distkeras_tpu.parallel.ps.InProcessClient` — trainers are
    transport-agnostic."""

    def __init__(
        self,
        host: str,
        port: int = DEFAULT_PORT,
        like: Any = None,
        rpc_timeout_s: float = 120.0,
        max_message_mb: int = 1024,
    ):
        # Every RPC carries a deadline: a wedged-but-alive PS must surface as
        # an error the HA retry layer can act on, not an eternal block.
        self._rpc_timeout_s = float(rpc_timeout_s)
        import grpc

        max_bytes = int(max_message_mb) * 1024 * 1024
        self._channel = grpc.insecure_channel(
            f"{host}:{port}",
            options=[
                ("grpc.max_receive_message_length", max_bytes),
                ("grpc.max_send_message_length", max_bytes),
            ],
        )
        self._pull = self._channel.unary_unary(
            f"/{_SERVICE}/pull",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        self._commit = self._channel.unary_unary(
            f"/{_SERVICE}/commit",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        self._commit_pull = self._channel.unary_unary(
            f"/{_SERVICE}/commit_pull",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        self._health = self._channel.unary_unary(
            f"/{_SERVICE}/health",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        self._like = like

    def pull(self) -> tuple[Any, int]:
        return _decode_pull_reply(
            self._pull(b"", timeout=self._rpc_timeout_s), like=self._like
        )

    def commit(self, payload: dict) -> None:
        self._commit(_encode_commit(payload), timeout=self._rpc_timeout_s)

    def commit_pull(self, payload: dict) -> tuple[Any, int]:
        """Fused commit+pull: one wire round trip per window (the reference's
        cadence over its socket PS — SURVEY §3.1)."""
        reply = self._commit_pull(_encode_commit(payload), timeout=self._rpc_timeout_s)
        return _decode_pull_reply(reply, like=self._like)

    def health(self, timeout: float = 5.0) -> dict:
        import json

        return json.loads(self._health(b"", timeout=timeout).decode())

    def close(self) -> None:
        self._channel.close()


def _id_to_array(cid: str) -> np.ndarray:
    return np.frombuffer(str(cid).encode("utf-8"), dtype=np.uint8).copy()


def _array_to_id(arr: np.ndarray) -> str:
    return bytes(np.asarray(arr, dtype=np.uint8)).decode("utf-8")
