from distkeras_tpu.parallel.mesh import (
    best_mesh,
    data_parallel_shardings,
    make_mesh,
)
from distkeras_tpu.parallel.protocols import (
    ADAGProtocol,
    AEASGDProtocol,
    AsyncProtocol,
    DOWNPOURProtocol,
    DynSGDProtocol,
    EAMSGDProtocol,
)
from distkeras_tpu.parallel.ps import InProcessClient, ParameterServerService


def __getattr__(name):
    # Heavier submodules resolved lazily.
    import importlib

    lazy = {
        "gspmd": "distkeras_tpu.parallel.gspmd",
        "pipeline": "distkeras_tpu.parallel.pipeline",
        "ha": "distkeras_tpu.parallel.ha",
        "distributed": "distkeras_tpu.parallel.distributed",
        "ps_grpc": "distkeras_tpu.parallel.ps_grpc",
        "sharding": "distkeras_tpu.parallel.sharding",
        "pp": "distkeras_tpu.parallel.pp",
    }
    if name in lazy:
        return importlib.import_module(lazy[name])
    raise AttributeError(name)


__all__ = [
    "make_mesh",
    "best_mesh",
    "data_parallel_shardings",
    "AsyncProtocol",
    "DOWNPOURProtocol",
    "ADAGProtocol",
    "AEASGDProtocol",
    "EAMSGDProtocol",
    "DynSGDProtocol",
    "ParameterServerService",
    "InProcessClient",
]
