from distkeras_tpu.parallel.mesh import (
    best_mesh,
    data_parallel_shardings,
    make_mesh,
)
from distkeras_tpu.parallel.protocols import (
    ADAGProtocol,
    AEASGDProtocol,
    AsyncProtocol,
    DOWNPOURProtocol,
    DynSGDProtocol,
    EAMSGDProtocol,
)
from distkeras_tpu.parallel.ps import InProcessClient, ParameterServerService

__all__ = [
    "make_mesh",
    "best_mesh",
    "data_parallel_shardings",
    "AsyncProtocol",
    "DOWNPOURProtocol",
    "ADAGProtocol",
    "AEASGDProtocol",
    "EAMSGDProtocol",
    "DynSGDProtocol",
    "ParameterServerService",
    "InProcessClient",
]
