"""Pipeline-stage planning for pp-sharded serving.

A ``pp=M`` serving mesh (``parallel/mesh.serving_mesh``) splits the
model into M contiguous layer *stages*, each owning its slice of the
encoder trunk plus the ends that anchor it: stage 0 owns the embedding
(``token_embed``, ``pos_embed`` and — dense decode — the ``pos_index``
cache counter), the last stage owns the final LayerNorm, the ``mlm_bias``
head and a second placed copy of ``token_embed`` (the tied head reads
the embedding matrix via ``embed.attend``). Stage parameters and KV
caches land ONLY on their stage's devices — the shard-then-place seam
(arXiv:2004.13336) extended from shards to stages, in the spirit of the
TensorFlow paper's dataflow device placement (arXiv:1605.08695).

The plan is pure bookkeeping over top-level pytree keys: the model is
always *initialized* whole, then :meth:`StagePlan.split_params` /
:meth:`StagePlan.split_tree` carve the param and cache trees into
per-stage subtrees whose keys match exactly what a stage-sliced
``Bert.__call__`` (``stage=(lo, hi, first, last)``) touches — so each
stage's jit sees precisely its own placed subtree, and a mismatch fails
loudly at trace time rather than silently replicating.
"""

from __future__ import annotations

import dataclasses

__all__ = ["StagePlan", "plan_stages"]


@dataclasses.dataclass(frozen=True)
class StagePlan:
    """Contiguous equal-size layer assignment of ``num_layers`` encoder
    layers onto ``num_stages`` pipeline stages."""

    num_layers: int
    num_stages: int

    @property
    def layers_per_stage(self) -> int:
        return self.num_layers // self.num_stages

    def layer_range(self, stage: int) -> tuple[int, int]:
        """``[lo, hi)`` layer indices owned by ``stage``."""
        if not 0 <= stage < self.num_stages:
            raise ValueError(
                f"stage {stage} out of range for pp={self.num_stages}")
        lo = stage * self.layers_per_stage
        return lo, lo + self.layers_per_stage

    def stage_arg(self, stage: int) -> tuple[int, int, bool, bool]:
        """The ``stage=`` argument for a stage-sliced model apply."""
        lo, hi = self.layer_range(stage)
        return (lo, hi, stage == 0, stage == self.num_stages - 1)

    def stage_of_layer(self, layer: int) -> int:
        if not 0 <= layer < self.num_layers:
            raise ValueError(f"layer {layer} out of range for "
                             f"{self.num_layers} layers")
        return layer // self.layers_per_stage

    def owner_stages(self, key: str) -> tuple[int, ...]:
        """Which stage(s) hold top-level param-tree key ``key``."""
        last = self.num_stages - 1
        if key == "token_embed":
            # Stage 0 embeds; the last stage's tied head reads the same
            # matrix via ``embed.attend`` — both get a placed copy.
            return (0,) if last == 0 else (0, last)
        if key == "pos_embed":
            return (0,)
        if key in ("ln_final", "mlm_bias"):
            return (last,)
        if key.startswith("layer_"):
            return (self.stage_of_layer(int(key[len("layer_"):])),)
        raise KeyError(f"no stage assignment for param key {key!r}")

    def split_params(self, params) -> list[dict]:
        """Per-stage param subtrees (top-level-key split; ``token_embed``
        appears on both stage 0 and the last stage)."""
        parts: list[dict] = [{} for _ in range(self.num_stages)]
        for key in params:
            for s in self.owner_stages(key):
                parts[s][key] = params[key]
        return parts

    def split_tree(self, tree) -> list[dict]:
        """Per-stage cache/KV subtrees: ``layer_i`` keys go to the
        layer's owning stage, the dense ``pos_index`` counter to stage 0
        (it feeds the embedding's positional slice)."""
        parts: list[dict] = [{} for _ in range(self.num_stages)]
        for key in tree:
            if key.startswith("layer_"):
                s = self.stage_of_layer(int(key[len("layer_"):]))
            elif key == "pos_index":
                s = 0
            else:
                raise KeyError(f"no stage assignment for cache key {key!r}")
            parts[s][key] = tree[key]
        return parts


def plan_stages(num_layers: int, num_stages: int) -> StagePlan:
    """Validated stage plan; raises ``ValueError`` (typed, CLI-surfaced)
    when the layer count cannot split into ``num_stages`` contiguous
    equal stages."""
    num_layers = int(num_layers)
    num_stages = int(num_stages)
    if num_stages < 1:
        raise ValueError(f"pp={num_stages} must be >= 1")
    if num_layers < num_stages:
        raise ValueError(
            f"pp={num_stages} stages need at least one layer each but "
            f"the model has {num_layers} layers")
    if num_layers % num_stages != 0:
        raise ValueError(
            f"num_layers={num_layers} does not divide into pp="
            f"{num_stages} contiguous equal stages; choose a pp that "
            f"divides the layer count")
    return StagePlan(num_layers=num_layers, num_stages=num_stages)
