"""High-availability wrappers for PS clients.

The reference had no failure handling: a PS crash hung every worker, and a
Spark task retry silently re-applied a partition's updates (at-least-once
skew — SURVEY §5). Here:

- :class:`RetryingClient` retries pull/commit with exponential backoff and
  surfaces a :class:`ParameterServerUnavailable` only after the budget is
  exhausted — transient DCN blips don't kill a training run;
- :class:`StampingClient` attaches a unique ``commit_id`` to every commit so
  the PS's dedupe window (``ParameterServerService.dedupe_window``) makes
  retried commits exactly-once;
- :func:`watchdog` polls a client's ``health`` and invokes a callback when
  the PS stops making progress.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

__all__ = [
    "ParameterServerUnavailable",
    "RetryingClient",
    "StampingClient",
    "CompressingClient",
    "watchdog",
]


class ParameterServerUnavailable(RuntimeError):
    pass


class RetryingClient:
    """Wrap any pull/commit client with retry + backoff."""

    def __init__(
        self,
        client,
        max_retries: int = 5,
        base_delay: float = 0.2,
        max_delay: float = 10.0,
        registry=None,
    ):
        self._client = client
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        # Optional telemetry (MetricsRegistry): retries are the early
        # warning of a degrading PS transport — a climbing counter shows
        # up on a scrape long before the retry budget finally exhausts.
        self._c_retries = None
        if registry is not None:
            self._c_retries = registry.counter(
                "ps_client_retries_total", help="PS call retries", op="any")

    def _with_retries(self, fn: Callable, what: str):
        delay = self.base_delay
        last_exc: BaseException | None = None
        for attempt in range(self.max_retries + 1):
            try:
                return fn()
            except Exception as e:  # transport-level failure
                last_exc = e
                if attempt < self.max_retries:  # no pointless final sleep
                    if self._c_retries is not None:
                        self._c_retries.inc()
                    time.sleep(delay)
                    delay = min(delay * 2, self.max_delay)
        raise ParameterServerUnavailable(
            f"{what} failed after {self.max_retries + 1} attempts"
        ) from last_exc

    def pull(self):
        return self._with_retries(self._client.pull, "pull")

    def commit(self, payload: dict) -> None:
        # Safe to retry only when the commit is idempotent (stamped).
        self._with_retries(lambda: self._client.commit(payload), "commit")

    def commit_pull(self, payload: dict):
        # Same idempotence story: the PS dedupe window makes a retried fused
        # exchange apply-at-most-once, and the dup path still replies.
        return self._with_retries(
            lambda: self._client.commit_pull(payload), "commit_pull"
        )

    def __getattr__(self, name: str) -> Any:
        return getattr(self._client, name)


class StampingClient:
    """Attach monotonically-unique commit_ids for exactly-once application."""

    def __init__(self, client, worker_id: int):
        self._client = client
        self._worker_id = int(worker_id)
        self._counter = 0

    def pull(self):
        return self._client.pull()

    def _stamp(self, payload: dict) -> dict:
        self._counter += 1
        # ``worker`` rides along for the health layer's per-worker
        # accounting (the commit_id encodes the same index, but parsing
        # it back out is a fallback, not the contract).
        return {**payload, "worker": self._worker_id,
                "commit_id": f"w{self._worker_id}:{self._counter}"}

    def commit(self, payload: dict) -> None:
        self._client.commit(self._stamp(payload))

    def commit_pull(self, payload: dict):
        return self._client.commit_pull(self._stamp(payload))

    def __getattr__(self, name: str) -> Any:
        return getattr(self._client, name)


class CompressingClient:
    """Cast commit deltas to bfloat16 before they leave the device/host —
    halves PS wire traffic (the DCN hop for remote islands). The center
    accumulates in float32 on the PS; numpy promotes bf16+f32 to f32, so
    protocol math is unchanged. Deltas are differences of nearby weights,
    so bf16's 8 mantissa bits cost little (same trade NCCL bf16 all-reduce
    makes); pulls stay full precision."""

    def __init__(self, client):
        self._client = client

    def pull(self):
        return self._client.pull()

    @staticmethod
    def _bf16(tree):
        import jax
        import jax.numpy as jnp
        import numpy as np

        return jax.tree.map(
            lambda x: np.asarray(jax.device_get(jnp.asarray(x).astype(jnp.bfloat16))),
            tree,
        )

    def commit(self, payload: dict) -> None:
        self._client.commit({**payload, "delta": self._bf16(payload["delta"])})

    def commit_pull(self, payload: dict):
        # Only deltas are compressed. A fused elastic exchange compresses
        # itself at the protocol layer (AEASGD ships bf16 mirror-diffs in
        # steady state; its bootstrap "local" frame must stay full precision
        # — absolute weights don't tolerate bf16 truncation the way
        # near-zero deltas do).
        if "delta" in payload:
            payload = {**payload, "delta": self._bf16(payload["delta"])}
        return self._client.commit_pull(payload)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._client, name)


def watchdog(
    health_fn: Callable[[], dict],
    on_stall: Callable[[dict], None],
    interval: float = 5.0,
    stall_after: int = 3,
    stop_event: threading.Event | None = None,
    registry=None,
) -> threading.Thread:
    """Background thread: calls ``health_fn`` every ``interval`` seconds and
    fires ``on_stall(last_health)`` after ``stall_after`` consecutive checks
    with no commit progress (or failed health calls). With a ``registry``,
    each fired stall also bumps ``ps_watchdog_stalls_total``."""
    stop_event = stop_event or threading.Event()
    c_stalls = None
    if registry is not None:
        c_stalls = registry.counter(
            "ps_watchdog_stalls_total", help="watchdog stall callbacks fired")

    def run():
        last_commits = -1
        stalls = 0
        while not stop_event.wait(interval):
            try:
                h = health_fn()
            except Exception:
                h = {"running": False, "num_commits": last_commits}
            if not h.get("running", False) or h.get("num_commits", 0) == last_commits:
                stalls += 1
                if stalls >= stall_after:
                    if c_stalls is not None:
                        c_stalls.inc()
                    on_stall(h)
                    stalls = 0
            else:
                stalls = 0
            last_commits = h.get("num_commits", last_commits)

    t = threading.Thread(target=run, name="ps-watchdog", daemon=True)
    t.stop_event = stop_event
    t.start()
    return t
