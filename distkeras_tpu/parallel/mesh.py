"""Device mesh and sharding utilities.

The reference has no notion of device topology — "parallelism" is Spark
partition count (``distkeras/trainers.py`` § ``DistributedTrainer.num_workers``).
Here the unit of scale is a ``jax.sharding.Mesh`` over TPU chips with named
axes, and parallelism strategies are sharding annotations:

- ``dp``   data parallel (batch split; gradient psum over ICI)
- ``fsdp`` fully-sharded data parallel (params sharded over the data axis)
- ``tp``   tensor parallel (weight matrices split; activation collectives)
- ``sp``   sequence/context parallel (long-context attention)
- ``pp``   pipeline stages

Sync data-parallel training (the reference's ``SynchronousDistributedTrainer``
/ ``AveragingTrainer`` use case) needs only ``dp``: shard the batch, let XLA
insert the gradient all-reduce over ICI.
"""

from __future__ import annotations

import math
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "AXES",
    "make_mesh",
    "best_mesh",
    "data_parallel_shardings",
    "shard_batch_spec",
]

AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")


def make_mesh(
    axis_sizes: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh with the given named axis sizes.

    Unnamed remainder devices fold into ``dp``. Example:
    ``make_mesh({"dp": 2, "tp": 4})`` on 8 devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axis_sizes or {})
    specified = math.prod(sizes.values()) if sizes else 1
    if n % specified != 0:
        raise ValueError(f"{n} devices not divisible by axis product {specified}")
    if "dp" not in sizes:
        sizes = {"dp": n // specified, **sizes}
    names = [a for a in AXES if a in sizes] + [a for a in sizes if a not in AXES]
    shape = [sizes[a] for a in names]
    if math.prod(shape) != n:
        raise ValueError(f"mesh {dict(zip(names, shape))} != {n} devices")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(names))


def best_mesh(num_devices: int | None = None) -> Mesh:
    """Default mesh: pure data-parallel over all local devices."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} "
                f"are attached; reduce num_workers or run on more chips"
            )
        devices = devices[:num_devices]
    return make_mesh({"dp": len(devices)}, devices=devices)


def shard_batch_spec(mesh: Mesh) -> P:
    """Batch dimension sharded over every data-like axis present."""
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    if not batch_axes:
        return P()
    return P(batch_axes if len(batch_axes) > 1 else batch_axes[0])


def data_parallel_shardings(mesh: Mesh):
    """(batch_sharding, replicated_sharding) for classic DP training."""
    batch = NamedSharding(mesh, shard_batch_spec(mesh))
    replicated = NamedSharding(mesh, P())
    return batch, replicated
