"""Device mesh and sharding utilities.

The reference has no notion of device topology — "parallelism" is Spark
partition count (``distkeras/trainers.py`` § ``DistributedTrainer.num_workers``).
Here the unit of scale is a ``jax.sharding.Mesh`` over TPU chips with named
axes, and parallelism strategies are sharding annotations:

- ``dp``   data parallel (batch split; gradient psum over ICI)
- ``fsdp`` fully-sharded data parallel (params sharded over the data axis)
- ``tp``   tensor parallel (weight matrices split; activation collectives)
- ``sp``   sequence/context parallel (long-context attention)
- ``pp``   pipeline stages

Sync data-parallel training (the reference's ``SynchronousDistributedTrainer``
/ ``AveragingTrainer`` use case) needs only ``dp``: shard the batch, let XLA
insert the gradient all-reduce over ICI.
"""

from __future__ import annotations

import math
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "AXES",
    "make_mesh",
    "best_mesh",
    "data_parallel_shardings",
    "parse_mesh_shape",
    "pp_stages",
    "serving_mesh",
    "shard_batch_spec",
    "stage_submesh",
]

AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")


def make_mesh(
    axis_sizes: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh with the given named axis sizes.

    Unnamed remainder devices fold into ``dp``. Example:
    ``make_mesh({"dp": 2, "tp": 4})`` on 8 devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axis_sizes or {})
    specified = math.prod(sizes.values()) if sizes else 1
    if n % specified != 0:
        raise ValueError(f"{n} devices not divisible by axis product {specified}")
    if "dp" not in sizes:
        sizes = {"dp": n // specified, **sizes}
    names = [a for a in AXES if a in sizes] + [a for a in sizes if a not in AXES]
    shape = [sizes[a] for a in names]
    if math.prod(shape) != n:
        raise ValueError(f"mesh {dict(zip(names, shape))} != {n} devices")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(names))


def best_mesh(num_devices: int | None = None) -> Mesh:
    """Default mesh: pure data-parallel over all local devices."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} "
                f"are attached; reduce num_workers or run on more chips"
            )
        devices = devices[:num_devices]
    return make_mesh({"dp": len(devices)}, devices=devices)


def parse_mesh_shape(spec: str) -> dict[str, int]:
    """Parse a CLI mesh-shape spec: ``"tp=2"``, ``"tp=2,dp=1"``, or a
    bare integer ``"4"`` (shorthand for ``tp=4``). Raises ``ValueError``
    on junk — the caller (``run.py serve --mesh-shape``) turns that into
    a typed CLI error instead of a deep jax traceback."""
    spec = (spec or "").strip()
    if not spec:
        raise ValueError("empty mesh shape; expected e.g. 'tp=2'")
    if spec.isdigit():
        return {"tp": int(spec)}
    shape: dict[str, int] = {}
    for part in spec.split(","):
        name, sep, size = part.partition("=")
        name = name.strip()
        if not sep or not name or not size.strip().isdigit():
            raise ValueError(
                f"bad mesh shape {spec!r}: each comma-separated entry "
                f"must be AXIS=N (e.g. 'tp=2'), got {part!r}")
        if name in shape:
            raise ValueError(f"bad mesh shape {spec!r}: axis {name!r} "
                             f"given twice")
        shape[name] = int(size.strip())
    for name, size in shape.items():
        if size < 1:
            raise ValueError(
                f"bad mesh shape {spec!r}: axis {name}={size} must be "
                f">= 1")
    return shape


def serving_mesh(
    shape: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh for ONE GSPMD-sharded serving replica.

    ``shape`` defaults to ``{"tp": <all visible devices>}`` — one big
    tensor-parallel replica. An explicit shape must **divide the visible
    device count** (the remainder hosts other replicas); a shape that
    does not raises ``ValueError`` with the counts spelled out, which
    ``run.py serve`` surfaces as a typed CLI error. Exactly the shape's
    device-product devices are used (the first ones, in ``jax.devices()``
    order) — a serving mesh never folds leftover devices into a hidden
    axis the way :func:`make_mesh` folds them into ``dp``.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n < 1:
        raise ValueError("no visible devices to build a serving mesh on")
    sizes = dict(shape) if shape else {"tp": n}
    if "tp" not in sizes:
        raise ValueError(
            f"serving mesh shape {sizes} has no 'tp' axis; tensor "
            f"parallelism is what a sharded serving replica shards over")
    extra = {a: s for a, s in sizes.items()
             if a not in ("tp", "pp") and s > 1}
    if extra:
        # Rejected HERE so the CLI layer fails one typed line before a
        # model loads (or a cluster spawns N children that would all
        # crash-loop in the engine ctor's identical check).
        raise ValueError(
            f"serving mesh has non-trivial non-tp/pp axes {extra}: data "
            f"parallelism in serving is N replicas (run.py cluster "
            f"--replicas), not a dp mesh axis inside one engine")
    need = math.prod(sizes.values())
    if need > n or n % need != 0:
        raise ValueError(
            f"mesh shape {sizes} needs {need} devices but {n} are "
            f"visible ({need} must divide {n}); adjust --mesh-shape or "
            f"force more host devices")
    names = [a for a in AXES if a in sizes] + [
        a for a in sizes if a not in AXES]
    dims = [sizes[a] for a in names]
    arr = np.array(devices[:need]).reshape(dims)
    return Mesh(arr, axis_names=tuple(names))


def pp_stages(mesh: Mesh | None) -> int:
    """Pipeline-stage count of a serving mesh (1 when unsharded or no
    ``pp`` axis)."""
    if mesh is None or "pp" not in mesh.axis_names:
        return 1
    return mesh.shape["pp"]


def stage_submesh(mesh: Mesh, stage: int) -> Mesh:
    """The tp-only sub-mesh of pipeline stage ``stage``.

    A jit's inputs must all live on one device set, so each stage
    compiles its callables against its own ``("tp",)`` mesh: the column
    of ``mesh.devices`` at pp-index ``stage``. Stage 0 on a ``tp=2,pp=2``
    mesh is ``devices[:, 0]``."""
    if "pp" not in mesh.axis_names:
        if stage != 0:
            raise ValueError(f"mesh has no pp axis but stage {stage} "
                             f"requested")
        return mesh
    pp_index = mesh.axis_names.index("pp")
    n_stages = mesh.devices.shape[pp_index]
    if not 0 <= stage < n_stages:
        raise ValueError(f"stage {stage} out of range for pp={n_stages}")
    col = np.take(mesh.devices, stage, axis=pp_index)
    return Mesh(col.reshape(-1), axis_names=("tp",))


def shard_batch_spec(mesh: Mesh) -> P:
    """Batch dimension sharded over every data-like axis present."""
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    if not batch_axes:
        return P()
    return P(batch_axes if len(batch_axes) > 1 else batch_axes[0])


def data_parallel_shardings(mesh: Mesh):
    """(batch_sharding, replicated_sharding) for classic DP training."""
    batch = NamedSharding(mesh, shard_batch_spec(mesh))
    replicated = NamedSharding(mesh, P())
    return batch, replicated
