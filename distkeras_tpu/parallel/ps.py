"""Single-owner parameter-server service.

Replaces the reference's socket parameter server
(``distkeras/parameter_servers.py`` § ``SocketParameterServer``: TCP accept
loop, thread-per-connection, handlers mutating center weights under the GIL).
Design differences, deliberate (SURVEY §5 race-detection note):

- **Single-owner state.** One service loop owns the center PyTree and the
  update counter; pulls and commits are messages consumed sequentially from
  one queue. Data races on PS state are impossible by construction — no
  locks, no GIL reliance.
- **Transport-agnostic.** :class:`InProcessClient` (queue-based, zero-copy)
  serves workers in the same process — the common case on a TPU host where
  workers are threads driving devices. The cross-host gRPC transport over
  DCN (:mod:`distkeras_tpu.parallel.ps_grpc`, standing in for the
  reference's ``distkeras/networking.py`` pickle-over-TCP framing, without
  pickle) plugs in behind the same pull/commit client interface.
- Center lives as host numpy arrays; commit math is vectorized numpy on the
  PS loop, off the device hot path.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any

import jax
import numpy as np

from distkeras_tpu.parallel.protocols import AsyncProtocol

__all__ = ["ParameterServerService", "InProcessClient"]

PyTree = Any

_PULL = "pull"
_COMMIT = "commit"
_COMMIT_PULL = "commit_pull"
_STOP = "stop"


from distkeras_tpu.utils.pytree import pytree_to_host as _to_host


class ParameterServerService:
    """The PS loop. Mirrors the reference lifecycle API
    (``ParameterServer.{initialize,run,stop}``, ``get_model`` —
    ``distkeras/parameter_servers.py`` § ``ParameterServer``)."""

    def __init__(
        self,
        protocol: AsyncProtocol,
        center: PyTree,
        num_workers: int,
        dedupe_window: int = 8192,
        registry=None,
        health=None,
    ):
        self.protocol = protocol
        self.num_workers = int(num_workers)
        self._center = _to_host(center)
        # Optional TrainingHealth (telemetry.training_health): per-commit
        # staleness/divergence/goodput accounting, fed from inside the
        # single-owner loop with the PRE-commit state each definition
        # needs. Its observe hooks swallow their own exceptions.
        self._health = health
        if health is not None:
            health.attach_ps(self)  # statusz folds in health() rollup
        # Optional telemetry (MetricsRegistry): live commit/duplicate
        # counters + queue-depth gauge, the scrapeable face of health().
        self._c_commits = self._c_dups = self._g_depth = None
        if registry is not None:
            self._c_commits = registry.counter(
                "ps_commits_total", help="PS commits applied")
            self._c_dups = registry.counter(
                "ps_duplicate_commits_total", help="deduped retried commits")
            self._g_depth = registry.gauge(
                "ps_queue_depth", help="pending PS messages")
        self._num_updates = 0
        self._num_commits = 0
        self._num_duplicates = 0
        # Idempotent commits: a retried/replayed commit (worker retry after a
        # transport error, task re-execution) is applied at most once. The
        # reference had at-least-once semantics here — Spark task retry
        # silently re-applied a partition's updates (SURVEY §5 failure notes).
        self._seen_ids: collections.OrderedDict = collections.OrderedDict()
        self._dedupe_window = int(dedupe_window)
        self._queue: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self.running = False
        # Incremented by the trainer's snapshot loop on checkpoint failures;
        # surfaced through health() so a dead snapshot loop is visible.
        self.snapshot_failures = 0

    # -- lifecycle -----------------------------------------------------------

    def initialize(self) -> None:  # reference API parity; state set in __init__
        pass

    def start(self) -> None:
        if self._thread is not None:
            return
        self.running = True
        self._thread = threading.Thread(target=self._run, name="ps-loop", daemon=True)
        self._thread.start()

    run = start  # reference calls it `run` on a thread; we manage the thread

    def stop(self) -> None:
        if self._thread is None:
            return
        self.running = False
        self._queue.put((_STOP, None, None))
        self._thread.join()
        self._thread = None

    # -- service loop (sole owner of _center/_num_updates) -------------------

    def _run(self) -> None:
        while True:
            action, payload, reply = self._queue.get()
            if self._g_depth is not None:
                self._g_depth.set(self._queue.qsize())
            if action == _STOP:
                break
            if action == _PULL:
                # Snapshot: copy so the worker can't observe later mutation.
                snap = jax.tree.map(np.copy, self._center)
                reply.put((snap, self._num_updates))
            elif action == _COMMIT:
                if self._is_duplicate(payload):
                    if reply is not None:
                        reply.put(False)
                    continue
                if self._health is not None:
                    # Host-convert the delta ONCE (idempotent; the
                    # protocol's host apply needs it anyway) so the
                    # health layer's norm pass doesn't add a second
                    # device-to-host transfer per commit.
                    if "delta" in payload:
                        payload["delta"] = _to_host(payload["delta"])
                    self._health.observe_commit(
                        self.protocol, self._center, self._num_updates,
                        payload, self.num_workers)
                self._center, self._num_updates = self.protocol.server_commit(
                    self._center, self._num_updates, payload, self.num_workers
                )
                self._num_commits += 1
                if self._c_commits is not None:
                    self._c_commits.inc()
                if reply is not None:
                    reply.put(True)
            elif action == _COMMIT_PULL:
                # Fused exchange: apply + reply in one PS transition — one
                # wire round trip per window (the reference's cadence,
                # SURVEY §3.1). A deduped retry still gets an answer.
                if self._is_duplicate(payload):
                    out = self.protocol.server_duplicate_reply(
                        self._center, self._num_updates, payload
                    )
                else:
                    before = self._num_updates
                    if self._health is not None:
                        # Pre-apply: staleness/divergence are defined
                        # against the state the committer raced with. A
                        # no-op exchange (elastic re-bootstrap answer)
                        # still counts the contact — its damping/norm
                        # fields are simply absent. Delta host-converted
                        # once, shared with the apply below.
                        if "delta" in payload:
                            payload["delta"] = _to_host(payload["delta"])
                        self._health.observe_commit(
                            self.protocol, self._center, self._num_updates,
                            payload, self.num_workers)
                    (
                        self._center,
                        self._num_updates,
                        out,
                    ) = self.protocol.server_commit_pull(
                        self._center, self._num_updates, payload, self.num_workers
                    )
                    # An unchanged counter means the protocol applied
                    # nothing (e.g. the elastic re-bootstrap answer) —
                    # don't report it as progress through health().
                    if self._num_updates != before:
                        self._num_commits += 1
                        if self._c_commits is not None:
                            self._c_commits.inc()
                tree, counter = out
                reply.put((jax.tree.map(np.copy, tree), counter))

    def _is_duplicate(self, payload: dict) -> bool:
        """Record-and-test the commit id (sole-owner loop; no locking).
        Idempotent commits: a retried/replayed commit is applied at most
        once (the reference's Spark-retry path was at-least-once)."""
        cid = payload.get("commit_id")
        if cid is None:
            return False
        if cid in self._seen_ids:
            self._num_duplicates += 1
            if self._c_dups is not None:
                self._c_dups.inc()
            if self._health is not None:
                self._health.record_duplicate(payload)
            return True
        self._seen_ids[cid] = None
        while len(self._seen_ids) > self._dedupe_window:
            self._seen_ids.popitem(last=False)
        return False

    # -- introspection -------------------------------------------------------

    def get_model(self) -> PyTree:
        """Final center weights (reference ``ParameterServer.get_model``).
        Only call after workers have stopped committing, or accept a
        point-in-time snapshot."""
        if self._thread is not None:
            reply: queue.Queue = queue.Queue()
            self._queue.put((_PULL, None, reply))
            center, _ = reply.get()
            return center
        return self._center

    @property
    def num_updates(self) -> int:
        return self._num_updates

    @property
    def num_commits(self) -> int:
        return self._num_commits

    @property
    def num_duplicates(self) -> int:
        return self._num_duplicates

    def health(self) -> dict:
        """Liveness + progress snapshot (reference PS had none; a wedged PS
        simply hung every worker — SURVEY §5)."""
        return {
            "running": self._thread is not None and self._thread.is_alive(),
            "num_updates": self._num_updates,
            "num_commits": self._num_commits,
            "num_duplicates": self._num_duplicates,
            "queue_depth": self._queue.qsize(),
            "snapshot_failures": self.snapshot_failures,
        }

    def client(self) -> "InProcessClient":
        return InProcessClient(self)


class InProcessClient:
    """Worker-side handle (reference ``distkeras/workers.py`` §
    ``NetworkWorker.pull``/``commit`` round-trips, minus the socket).

    ``wire_is_local``: the "wire" is a same-process queue — bytes are
    free and replies cannot be lost, so protocols should skip
    wire-compression state machines (bf16 delta mirrors, dedupe replay)
    that only pay on a real network. See
    ``AEASGDProtocol.worker_window``."""

    wire_is_local = True

    def __init__(self, service: ParameterServerService):
        self._service = service

    def pull(self) -> tuple[PyTree, int]:
        reply: queue.Queue = queue.Queue()
        self._service._queue.put((_PULL, None, reply))
        return reply.get()

    def commit(self, payload: dict) -> None:
        # Fire-and-forget, like the reference's one-way commit send; device
        # arrays are materialized to host numpy before enqueue so the PS
        # never touches device buffers.
        self._service._queue.put((_COMMIT, _host_payload(payload), None))

    def commit_pull(self, payload: dict) -> tuple[PyTree, int]:
        """Fused commit + pull: one queue round trip, one PS transition."""
        reply: queue.Queue = queue.Queue()
        self._service._queue.put((_COMMIT_PULL, _host_payload(payload), reply))
        return reply.get()


def _host_payload(payload: dict) -> dict:
    return {
        k: (_to_host(v) if k in ("delta", "local", "elastic_diff") else v)
        for k, v in payload.items()
    }
