"""Multi-host process-group bootstrap and async-island topology.

The reference's control plane is Spark: the driver pickles worker closures
into executors and discovers itself with ``determine_host_address()``
(SURVEY §1 "control plane = Spark driver"). The TPU-native control plane is
``jax.distributed``: every host runs the same SPMD program, the coordinator
address plays the driver's role, and data/gradient traffic never touches
the control plane.

Two usage patterns:

- **Sync (the default path):** ``initialize()`` on every host, build one
  global mesh with :func:`global_mesh`, train with
  ``SynchronousDistributedTrainer``/GSPMD — XLA collectives ride ICI.
- **Async islands (the Downpour-family path at multi-pod scale):** each
  island (pod slice) trains sync internally; one process per island speaks
  to the PS over DCN via :mod:`distkeras_tpu.parallel.ps_grpc`.
  :class:`IslandSpec` carries that wiring.
"""

from __future__ import annotations

import dataclasses
import os

import jax

from distkeras_tpu.parallel.mesh import make_mesh

__all__ = ["initialize", "global_mesh", "IslandSpec", "local_island"]


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize the JAX process group (no-op on a single host).

    Arguments default from the standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``)
    or the TPU metadata when running on a real pod.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None and num_processes is None:
        return  # single-host
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(axis_sizes: dict[str, int] | None = None):
    """A mesh over every device in the process group (all hosts)."""
    return make_mesh(axis_sizes, devices=jax.devices())


@dataclasses.dataclass(frozen=True)
class IslandSpec:
    """One async island: a sync SPMD group that talks to a remote PS.

    ``island_id``/``num_islands`` index this island among its peers (the
    trainer's ``num_workers`` at island granularity); ``ps_host``/``ps_port``
    locate the gRPC PS (DCN). Within the island, training is ordinary
    GSPMD over ``mesh_axes``.
    """

    island_id: int
    num_islands: int
    ps_host: str
    ps_port: int
    mesh_axes: tuple[tuple[str, int], ...] = ()

    def mesh(self):
        return global_mesh(dict(self.mesh_axes) or None)

    def client(self, like=None):
        from distkeras_tpu.parallel.ps_grpc import GrpcClient

        return GrpcClient(self.ps_host, self.ps_port, like=like)


def local_island(ps_host: str, ps_port: int, num_islands: int = 1) -> IslandSpec:
    """IslandSpec for this process group, numbering islands by the JAX
    process index (island 0 conventionally co-hosts the PS)."""
    pid = jax.process_index() if jax.process_count() > 1 else 0
    return IslandSpec(
        island_id=pid % num_islands,
        num_islands=num_islands,
        ps_host=ps_host,
        ps_port=ps_port,
    )
