"""Pipeline parallelism over a ``pp`` mesh axis (GPipe + Megatron-style
interleaved virtual stages; the backward is the scan's autodiff
time-reversal — GPipe-ordered, not 1F1B). The activation-memory price of
that choice is measured, not guessed: ``BENCH_MODE=memory
benchmarks/pipeline_bench.py`` reports XLA's compiled peak temp per
schedule (plain vs remat, V=1 vs 2) next to the TRUE 1F1B engine
(:mod:`distkeras_tpu.parallel.pipeline_1f1b` — hand-rolled backward,
near-flat residency in M: O(P) saved stage activations plus one M-sized
cotangent buffer); the (model, M, V, P)-fits-16GB table lives in
docs/parallel.md.

Absent from the reference (SURVEY §2 parallelism table) but a first-class
axis here. The design is SPMD, not a scheduler: every device runs the same
program under ``shard_map``; stage identity comes from ``lax.axis_index``.
Per tick, each device applies one of its stages to its current activation
and rotates activations one hop forward with ``lax.ppermute`` (ICI neighbor
traffic only).

**Schedule.** With ``V = virtual_stages`` chunks per device (Megatron-style
interleaving), the ``L = V·P`` logical stages are laid out round-robin:
device ``d`` owns logical stages ``{d, P+d, …, (V-1)·P+d}``. Microbatches
inject in groups of ``P`` at ticks ``inj(m) = (m//P)·V·P + m%P``; an
activation processed on device ``P-1`` for chunk ``v`` re-enters device 0
for chunk ``v+1`` on the very next tick, so nothing ever queues and the
lock-step rotation stays exact. Device ``d`` is busy every tick of
``[d, d+M·V)`` processing chunk ``v(τ) = (τ//P) mod V`` of microbatch
``m(τ) = (τ//(V·P))·P + τ%P`` where ``τ = t - d``. When ``P | M`` the total
is ``M·V + P - 1`` ticks and the fill/drain bubble is ``(P-1)/(M·V+P-1)`` —
**V× smaller per unit work** than the V=1 GPipe schedule's ``(P-1)/(M+P-1)``
(same-depth model, stages V× shallower). A ragged last group (``P ∤ M``)
still computes correctly but stalls up to one extra V·P round
(T = ((M-1)//P)·V·P + (M-1)%P + V·P); size ``M`` as a multiple of ``P`` to
get the advertised bubble. V=1 reduces to plain GPipe.

Constraints (by construction of the rotation): every stage maps activations
of one shape to the same shape — the transformer-block case. Embedding/head
layers stay outside the pipelined trunk.

The whole schedule is a ``lax.scan``, so it differentiates: gradients flow
back through the ppermutes (reverse hops) and the per-stage applications,
giving pipeline-parallel *training*, not just inference. (The backward is
the scan's time-reversal — activation memory is the remat lever on
``stage_fn``, not the schedule; see PipelineTrainer's ``remat``, or
``schedule="1f1b"`` for the hand-rolled schedule whose residency is
near-flat in M — and which composes with MoE/ep since round 5.)
"""

from __future__ import annotations

from distkeras_tpu.utils.platform import axis_size as _axis_size
from distkeras_tpu.utils.platform import pcast as _pcast

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "pipeline_apply",
    "stack_stage_params",
    "stage_param_specs",
    "pipeline_shardings",
    "schedule_ticks",
]


def stage_param_specs(stacked, ep_size: int = 1):
    """Per-leaf PartitionSpecs for stacked stage params: the stage axis
    shards over ``pp`` everywhere; MoE expert-weight leaves
    (``moe_mlp/w_in|w_out`` — leading stage dim, then the expert dim)
    additionally shard their expert dim over ``ep`` when ``ep_size > 1``.
    The router stays replicated over ep (every member routes the full
    token set). The ONE definition of the rule — the trainer, the memory
    bench, and the dryrun must all agree on which leaves are experts."""

    def spec(path, _leaf):
        if ep_size > 1:
            keys = [getattr(k, "key", None) for k in path]
            if "moe_mlp" in keys and keys[-1] in ("w_in", "w_out"):
                return P("pp", "ep")
        return P("pp")

    return jax.tree_util.tree_map_with_path(spec, stacked)


def schedule_ticks(num_microbatches: int, num_devices: int,
                   virtual_stages: int = 1) -> int:
    """Total scan ticks of the interleaved schedule: microbatch ``M-1``
    injects at ``((M-1)//P)·V·P + (M-1)%P`` and takes ``V·P`` ticks to
    drain. The ONE definition — the scan body, the schedule bench, and the
    memory bench all derive their tick counts from it."""
    M, P, V = num_microbatches, num_devices, virtual_stages
    return ((M - 1) // P) * V * P + (M - 1) % P + V * P


def stack_stage_params(stage_params_list, virtual_stages: int = 1):
    """Stack per-stage parameter PyTrees on a leading 'stage' axis
    ([L, ...] leaves) — shard that axis over ``pp``.

    ``stage_params_list`` is in **logical order** (stage 0 first). With
    ``virtual_stages=V > 1`` the stack is permuted to the round-robin device
    layout the interleaved schedule expects: position ``d·V + v`` holds
    logical stage ``v·P + d``, so the pp-sharding's contiguous split hands
    device ``d`` exactly its V chunks, indexable by ``v``.
    """
    L = len(stage_params_list)
    if L % virtual_stages:
        raise ValueError(
            f"{L} stages not divisible by virtual_stages={virtual_stages}"
        )
    if virtual_stages > 1:
        num_devices = L // virtual_stages
        order = [
            v * num_devices + d
            for d in range(num_devices)
            for v in range(virtual_stages)
        ]
        stage_params_list = [stage_params_list[i] for i in order]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params_list)


def _io_spec(mesh: Mesh) -> P:
    """Microbatch spec ``[M, B, ...]``: shard the batch axis over ``dp``
    when the mesh has one (each dp slice runs its own pipeline replica over
    the pp axis) instead of replicating the whole feed to every device."""
    if "dp" in mesh.axis_names and mesh.shape["dp"] > 1:
        return P(None, "dp")
    return P()


def pipeline_shardings(mesh: Mesh):
    """(stacked_params_sharding, io_sharding) for :func:`pipeline_apply`."""
    params = NamedSharding(mesh, P("pp"))
    io = NamedSharding(mesh, _io_spec(mesh))
    return params, io


def _pipeline_local(
    stage_fn, stacked_params, microbatches, rng, axis_name: str,
    virtual_stages: int, varying_axes=(), with_aux: bool = False,
):
    """Per-device body (inside shard_map).

    ``stacked_params``: this device's chunk params ([V, ...] leaves — the
    'pp'-sharded round-robin stack). ``microbatches``: [M, B, D]. Returns
    [M, B, D]: final-stage outputs (valid on every device: one psum after
    the scan broadcasts them, keeping collectives off the scan's critical
    path).
    """
    d = lax.axis_index(axis_name)
    num_devices = _axis_size(axis_name)
    V = virtual_stages
    M, B = microbatches.shape[0], microbatches.shape[1]
    feat_shape = microbatches.shape[2:]
    perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]

    def v_of(tau):
        return (tau // num_devices) % V

    def m_of(tau):
        return (tau // (V * num_devices)) * num_devices + tau % num_devices

    # The carry must be device-varying over the pp axis from the start
    # (ppermute outputs are varying; scan carries must type-match) — and
    # over any axis the microbatches are sharded on (dp io sharding makes
    # the ingested state dp-varying too).
    zeros = jnp.zeros((B, *feat_shape), microbatches.dtype)
    state = _pcast(zeros, (axis_name, *varying_axes), to="varying")
    out_buf = _pcast(
        jnp.zeros((M, B, *feat_shape), microbatches.dtype),
        (axis_name, *varying_axes),
        to="varying",
    )
    aux_acc = _pcast(
        jnp.zeros((), jnp.float32), (axis_name, *varying_axes), to="varying"
    )

    def tick(carry, t):
        state, out_buf, aux_acc = carry
        tau = t - d
        v = v_of(tau)
        m = m_of(tau)
        m_clip = jnp.clip(m, 0, M - 1)
        # device 0 ingests microbatch m when it starts chunk 0
        x_in = lax.dynamic_index_in_dim(
            microbatches, m_clip, axis=0, keepdims=False
        )
        ingest = (d == 0) & (v == 0) & (tau >= 0) & (m < M)
        state = jnp.where(ingest, x_in, state)
        my_params = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, v, axis=0, keepdims=False),
            stacked_params,
        )
        if rng is None:
            y = stage_fn(my_params, state)
        else:
            # unique stream per (tick, device, dp-slice): stochastic layers
            # (dropout) get fresh masks for every stage application of
            # every microbatch — including across dp replicas, whose data
            # shards differ and must not share masks
            key = jax.random.fold_in(jax.random.fold_in(rng, t), d)
            for _ax in varying_axes:
                key = jax.random.fold_in(key, lax.axis_index(_ax))
            y = stage_fn(my_params, state, key)
        if with_aux:
            y, aux = y
            # only real (stage, microbatch) applications contribute — the
            # fill/drain garbage ticks run on zero states and are masked out
            valid = (tau >= 0) & (m < M)
            aux_acc = aux_acc + jnp.where(valid, aux.astype(jnp.float32), 0.0)
        # the last device at its last chunk owns microbatch m's final output
        emit = (d == num_devices - 1) & (v == V - 1) & (tau >= 0) & (m < M)
        emitted = jnp.where(emit, y, jnp.zeros_like(y))
        out_buf = out_buf.at[m_clip].add(emitted)
        state = lax.ppermute(y, axis_name, perm)
        return (state, out_buf, aux_acc), None

    # Static tick count: last microbatch M-1 emits at inj(M-1) + V·P - 1
    # (axis_size of a mesh axis is a static int, so T is trace-time known).
    T = schedule_ticks(M, num_devices, V)
    (_, out_buf, aux_acc), _ = lax.scan(
        tick, (state, out_buf, aux_acc), jnp.arange(T)
    )
    out = lax.psum(out_buf, axis_name)
    if not with_aux:
        return out
    # every (logical stage, microbatch) pair ran on exactly one device: the
    # psum over pp is a disjoint sum, and dp replicas (different batch
    # shards) average.
    aux = lax.psum(aux_acc, axis_name)
    for ax in varying_axes:
        aux = lax.pmean(aux, ax)
    return out, aux


def pipeline_apply(
    stage_fn,
    stacked_params,
    microbatches,
    mesh: Mesh,
    axis_name: str = "pp",
    io_spec: P | None = None,
    virtual_stages: int = 1,
    rng=None,
    with_aux: bool = False,
    param_specs=None,
):
    """Run an ``L``-stage pipeline over ``mesh[axis_name]``.

    - ``stage_fn(params, x) -> y`` with ``y.shape == x.shape``;
    - ``stacked_params``: PyTree with leading stage axis (see
      :func:`stack_stage_params` — pass it the same ``virtual_stages`` so
      the round-robin layout matches), sharded over ``axis_name``;
    - ``microbatches``: ``[M, B, ...]`` array. By default the batch axis
      shards over the mesh's ``dp`` axis when present (each dp slice runs
      its own pipeline replica); pass ``io_spec`` to override.
    - ``virtual_stages``: chunks per device (interleaved schedule); the
      fill/drain bubble shrinks ~V× at the cost of V× more (shallower)
      stage applications per tick window.
    - ``rng``: optional PRNG key. When given, ``stage_fn`` is called as
      ``stage_fn(params, x, key)`` with a key unique per (tick, device) —
      the hook for stochastic layers (dropout) inside the pipelined trunk.
    - ``with_aux``: ``stage_fn`` returns ``(y, aux_scalar)``; the scalars
      from every real stage application are summed across stages, summed
      across microbatches, and averaged over dp replicas — the MoE
      load-balance-loss plumbing. Returns ``(outputs, aux_sum)``; divide by
      M for the per-batch mean.

    Returns ``[M, B, ...]`` — the final stage's outputs (plus the aux sum
    when ``with_aux``). Differentiable end-to-end.
    """
    from distkeras_tpu.utils.platform import get_shard_map

    shard_map = get_shard_map()

    if io_spec is None:
        io_spec = _io_spec(mesh)
    varying_axes = tuple(
        ax
        for entry in io_spec
        if entry is not None
        for ax in ((entry,) if isinstance(entry, str) else tuple(entry))
        if ax != axis_name
    )
    if param_specs is None:
        # Uniform default: stage axis over pp, everything else replicated.
        # Callers sharding further axes (e.g. expert dims over ep for a
        # pipelined MoE — the stage_fn then owns the matching collectives)
        # pass a per-leaf spec tree.
        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fn = shard_map(
        partial(
            _pipeline_local, stage_fn, axis_name=axis_name,
            virtual_stages=virtual_stages, varying_axes=varying_axes,
            with_aux=with_aux,
        ),
        mesh=mesh,
        in_specs=(param_specs, io_spec, P()),
        out_specs=(io_spec, P()) if with_aux else io_spec,
    )
    if microbatches.shape[0] < 1:
        raise ValueError("need at least one microbatch")
    expected = virtual_stages * mesh.shape[axis_name]
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead != expected:
        raise ValueError(
            f"stacked params have {lead} stages but mesh {axis_name}="
            f"{mesh.shape[axis_name]} x virtual_stages={virtual_stages} "
            f"needs {expected} — pass the same virtual_stages to "
            f"stack_stage_params and pipeline_apply"
        )
    return fn(stacked_params, microbatches, rng)
