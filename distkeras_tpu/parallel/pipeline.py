"""Pipeline parallelism over a ``pp`` mesh axis (GPipe-style microbatching).

Absent from the reference (SURVEY §2 parallelism table) but a first-class
axis here. The design is SPMD, not a scheduler: every device runs the same
program under ``shard_map``; stage identity comes from ``lax.axis_index``.
Per tick, each device applies *its* stage to its current activation and
rotates activations one hop forward with ``lax.ppermute`` (ICI neighbor
traffic only). A pipeline of P stages fed M microbatches drains in
``M + P - 1`` ticks — the classic GPipe bubble of (P-1)/(M+P-1).

Constraints (by construction of the rotation): every stage maps activations
of one shape to the same shape — the transformer-block case. Embedding/head
layers stay outside the pipelined trunk.

The whole schedule is a ``lax.scan``, so it differentiates: gradients flow
back through the ppermutes (reverse hops) and the per-stage applications,
giving pipeline-parallel *training*, not just inference.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["pipeline_apply", "stack_stage_params", "pipeline_shardings"]


def stack_stage_params(stage_params_list):
    """Stack per-stage parameter PyTrees on a leading 'stage' axis
    ([P, ...] leaves) — shard that axis over ``pp``."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params_list)


def _io_spec(mesh: Mesh) -> P:
    """Microbatch spec ``[M, B, ...]``: shard the batch axis over ``dp``
    when the mesh has one (each dp slice runs its own pipeline replica over
    the pp axis) instead of replicating the whole feed to every device."""
    if "dp" in mesh.axis_names and mesh.shape["dp"] > 1:
        return P(None, "dp")
    return P()


def pipeline_shardings(mesh: Mesh):
    """(stacked_params_sharding, io_sharding) for :func:`pipeline_apply`."""
    params = NamedSharding(mesh, P("pp"))
    io = NamedSharding(mesh, _io_spec(mesh))
    return params, io


def _pipeline_local(
    stage_fn, stacked_params, microbatches, axis_name: str, varying_axes=()
):
    """Per-device body (inside shard_map).

    ``stacked_params``: this device's stage params ([1, ...] leaves —
    the 'pp'-sharded stack). ``microbatches``: [M, B, D] (replicated).
    Returns [M, B, D]: outputs of the final stage (valid on every device:
    results are rotated full-circle so the scan output lands everywhere).
    """
    p = lax.axis_index(axis_name)
    num_stages = lax.axis_size(axis_name)
    my_params = jax.tree.map(lambda x: x[0], stacked_params)
    M, B = microbatches.shape[0], microbatches.shape[1]
    feat_shape = microbatches.shape[2:]
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    # The carry must be device-varying over the pp axis from the start
    # (ppermute outputs are varying; scan carries must type-match) — and
    # over any axis the microbatches are sharded on (dp io sharding makes
    # the ingested state dp-varying too).
    zeros = jnp.zeros((B, *feat_shape), microbatches.dtype)
    state = lax.pcast(zeros, (axis_name, *varying_axes), to="varying")

    def tick(carry, t):
        state = carry
        # stage 0 ingests microbatch t (clamped; masked when t >= M)
        x_in = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        state = jnp.where(p == 0, jnp.where(t < M, x_in, state), state)
        y = stage_fn(my_params, state)
        # the last stage owns microbatch (t - P + 1)'s final output; other
        # devices contribute zeros and ONE psum after the scan broadcasts
        # the results (keeping collectives off the scan's critical path).
        emitted = jnp.where(p == num_stages - 1, y, jnp.zeros_like(y))
        state = lax.ppermute(y, axis_name, perm)
        return state, emitted

    _, emitted_seq = lax.scan(tick, state, jnp.arange(M + num_stages - 1))
    emitted_seq = lax.psum(emitted_seq, axis_name)
    # microbatch m is emitted at tick m + P - 1
    return emitted_seq[num_stages - 1 :]


def pipeline_apply(
    stage_fn,
    stacked_params,
    microbatches,
    mesh: Mesh,
    axis_name: str = "pp",
    io_spec: P | None = None,
):
    """Run a P-stage pipeline over ``mesh[axis_name]``.

    - ``stage_fn(params, x) -> y`` with ``y.shape == x.shape``;
    - ``stacked_params``: PyTree with leading stage axis (see
      :func:`stack_stage_params`), sharded over ``axis_name``;
    - ``microbatches``: ``[M, B, ...]`` array. By default the batch axis
      shards over the mesh's ``dp`` axis when present (each dp slice runs
      its own pipeline replica); pass ``io_spec`` to override.

    Returns ``[M, B, ...]`` — the final stage's outputs. Differentiable
    end-to-end.
    """
    from jax import shard_map

    if io_spec is None:
        io_spec = _io_spec(mesh)
    varying_axes = tuple(
        ax
        for entry in io_spec
        if entry is not None
        for ax in ((entry,) if isinstance(entry, str) else tuple(entry))
        if ax != axis_name
    )
    spec_params = P(axis_name)
    fn = shard_map(
        partial(
            _pipeline_local, stage_fn, axis_name=axis_name,
            varying_axes=varying_axes,
        ),
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: spec_params, stacked_params), io_spec),
        out_specs=io_spec,
    )
    if microbatches.shape[0] < 1:
        raise ValueError("need at least one microbatch")
    return fn(stacked_params, microbatches)
