"""Logical-axis → mesh-axis sharding rules (GSPMD).

One model definition (annotated with ``nn.with_logical_partitioning``,
see :mod:`distkeras_tpu.models.bert`) maps onto any mesh by resolving its
logical axes against these rules — the "pick a mesh, annotate shardings,
let XLA insert collectives" recipe. The reference has no analogue: its only
notion of placement is "which Spark partition" (SURVEY §2 parallelism table:
TP/SP absent from dist-keras; provided here because BASELINE config #5
requires data+model sharding).
"""

from __future__ import annotations

import jax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DEFAULT_RULES",
    "kv_pytree_shardings",
    "logical_axis_rules",
    "infer_variable_shardings",
    "replicated",
]

# logical axis -> mesh axis (None = replicate)
DEFAULT_RULES: tuple[tuple[str, str | None], ...] = (
    ("batch", "dp"),
    ("seq", "sp"),
    ("embed", None),     # keep the residual stream replicated
    ("heads", "tp"),
    ("mlp", "tp"),
    ("kv", None),
    ("vocab", "tp"),
    ("expert", "ep"),
)


def logical_axis_rules(mesh: Mesh, overrides=None):
    """Filter DEFAULT_RULES down to axes the mesh actually has."""
    rules = []
    seen = set()
    for logical, phys in tuple(overrides or ()) + DEFAULT_RULES:
        if logical in seen:
            continue
        seen.add(logical)
        rules.append((logical, phys if phys in mesh.axis_names else None))
    return tuple(rules)


def infer_variable_shardings(mesh: Mesh, abstract_variables, overrides=None):
    """Resolve a variables PyTree (possibly containing
    ``nn.Partitioned`` leaves from logical annotations) to NamedShardings.

    Un-annotated leaves are replicated. Returns a PyTree of NamedSharding
    matching the *unboxed* variables structure.
    """
    rules = logical_axis_rules(mesh, overrides)
    logical_specs = nn.get_partition_spec(abstract_variables)
    mesh_specs = nn.logical_to_mesh(logical_specs, rules)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        mesh_specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def kv_pytree_shardings(mesh: Mesh, tree, axis: str = "tp"):
    """Shardings for a decode KV cache/pool pytree: every K/V leaf is
    sharded over its **heads** dimension, everything else replicated.

    The rule is shape-driven because cache variables carry no logical-
    axis annotations (they are created with plain ``self.variable``):
    K/V leaves are the ``ndim >= 3`` arrays — dense per-slot caches
    ``[B, L, H, D]``, single prefill rows ``[1, L, H, D]``, and paged
    block pools ``[C, block_tokens, H, D]`` all keep heads at axis
    ``-2`` — and shard only when the head count divides the mesh axis.
    1-D index leaves (cache/pos counters) and anything else stay
    replicated host-ish metadata, mirroring the serving engine's stance
    that block tables and slot state are replicated while only the KV
    bytes shard. ``tree`` may hold concrete arrays or ``eval_shape``
    structs."""
    n = mesh.shape.get(axis, 1)

    def rule(leaf):
        shape = getattr(leaf, "shape", ())
        if (axis in mesh.axis_names and n > 1 and len(shape) >= 3
                and shape[-2] % n == 0):
            spec = [None] * len(shape)
            spec[-2] = axis
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(rule, tree)


def unbox(variables):
    """Strip ``nn.Partitioned`` boxes, leaving raw arrays."""
    return nn.meta.unbox(variables)
