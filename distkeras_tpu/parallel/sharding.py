"""Logical-axis → mesh-axis sharding rules (GSPMD).

One model definition (annotated with ``nn.with_logical_partitioning``,
see :mod:`distkeras_tpu.models.bert`) maps onto any mesh by resolving its
logical axes against these rules — the "pick a mesh, annotate shardings,
let XLA insert collectives" recipe. The reference has no analogue: its only
notion of placement is "which Spark partition" (SURVEY §2 parallelism table:
TP/SP absent from dist-keras; provided here because BASELINE config #5
requires data+model sharding).
"""

from __future__ import annotations

import jax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DEFAULT_RULES",
    "logical_axis_rules",
    "infer_variable_shardings",
    "replicated",
]

# logical axis -> mesh axis (None = replicate)
DEFAULT_RULES: tuple[tuple[str, str | None], ...] = (
    ("batch", "dp"),
    ("seq", "sp"),
    ("embed", None),     # keep the residual stream replicated
    ("heads", "tp"),
    ("mlp", "tp"),
    ("kv", None),
    ("vocab", "tp"),
    ("expert", "ep"),
)


def logical_axis_rules(mesh: Mesh, overrides=None):
    """Filter DEFAULT_RULES down to axes the mesh actually has."""
    rules = []
    seen = set()
    for logical, phys in tuple(overrides or ()) + DEFAULT_RULES:
        if logical in seen:
            continue
        seen.add(logical)
        rules.append((logical, phys if phys in mesh.axis_names else None))
    return tuple(rules)


def infer_variable_shardings(mesh: Mesh, abstract_variables, overrides=None):
    """Resolve a variables PyTree (possibly containing
    ``nn.Partitioned`` leaves from logical annotations) to NamedShardings.

    Un-annotated leaves are replicated. Returns a PyTree of NamedSharding
    matching the *unboxed* variables structure.
    """
    rules = logical_axis_rules(mesh, overrides)
    logical_specs = nn.get_partition_spec(abstract_variables)
    mesh_specs = nn.logical_to_mesh(logical_specs, rules)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        mesh_specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def unbox(variables):
    """Strip ``nn.Partitioned`` boxes, leaving raw arrays."""
    return nn.meta.unbox(variables)
