"""True 1F1B pipeline schedule with a hand-rolled backward (one SPMD scan).

The scanned GPipe engine (:mod:`distkeras_tpu.parallel.pipeline`) gets its
backward from the scan's autodiff time-reversal: every (stage, tick)
residual stays live until the reversed scan consumes it, so peak
activation residency grows with the microbatch count ``M`` (measured in
``BENCH_MODE=memory benchmarks/pipeline_bench.py``). The classic fix —
the PipeDream-flush / Megatron "1F1B" schedule — cannot be expressed
through scan autodiff because it *interleaves* forward and backward work;
this module therefore writes the backward by hand.

**Schedule.** Non-interleaved 1F1B over ``P = mesh['pp']`` devices.
Device ``d`` runs the forward of microbatch ``m`` at tick ``2m + d`` and
its backward at tick ``2m + 2P - 1 - d``; the two assignments can never
collide (their tick parities differ), each device strictly alternates
F/B in steady state, neighbouring devices are phase-shifted by one tick
(activations hop ``d -> d+1``, cotangents hop ``d -> d-1``, one
``ppermute`` each per tick), and the whole step is ONE ``lax.scan`` of
``2M + 2P - 2`` ticks.

**Memory.** A device keeps only the *stage inputs* of microbatches whose
backward has not run yet — at most ``ceil((2P - 1 - 2d) / 2) <= P`` of
them, held in a ring buffer — and recomputes the stage forward inside
``jax.vjp`` at the backward tick (Megatron-style activation
recomputation). The *saved stage activations* are therefore O(P)
microbatch states per device independent of M, vs the scanned engine's
O(M·V). Total carry residency still has an M-sized term — the
``[M, B, ...]`` float32 input-cotangent buffer (``cot_out``) — so the
analytic floor is ``(min(P, M) + M)`` microbatch states, linear in M
with a much smaller constant than the scanned schedule (measured:
9-13 MB across M=8..32 vs 171-439 MB for gpipe-plain on the bench
model — the M term is ONE tensor, not one per stage tick). The
replicated ``[M, B, ...]`` microbatch inputs are additional M-linear
residency, but they live in the XLA argument buffers (reported as
``args_mb`` in the bench), not the temp/carry floor pinned above.
Compute matches the scanned engine with ``remat=True`` (one extra
forward per stage application).

**Loss placement.** 1F1B needs each microbatch's output cotangent the
tick after its last-stage forward, so the head + loss must live *inside*
the pipe: the last device's backward runs ``jax.vjp`` through
``last_fn(stage_params, head_params, x, labels)`` (stage -> head -> scalar
loss) with cotangent seed 1. The pipeline input's cotangent is emitted
per microbatch so the caller can backpropagate into the embedding that
produced the microbatches.

**Stochastic layers.** With ``rng``, each stage application receives a
key folded from (microbatch, stage, dp-slice) — deterministic, so the
backward tick's recompute reproduces the forward tick's dropout masks
exactly, and distinct across dp replicas so different data shards never
share masks.

**Data parallelism.** Pass ``io_spec`` (e.g. ``P(None, "dp")``) to shard
the microbatch batch axis: each dp slice runs its own 1F1B pipe; losses,
auxes, and parameter gradients are ``pmean``-ed over the dp axes (the
mean-loss convention), input cotangents stay dp-sharded like the inputs.

The result is a *value-and-grad* primitive, not a differentiable forward:
``pipeline_1f1b_value_and_grad`` returns the summed loss, the stacked
per-stage parameter gradients, the head gradients, and the per-microbatch
input cotangents. No reference counterpart exists (SURVEY §2: pipeline
parallelism absent from the reference entirely).
"""

from __future__ import annotations

from distkeras_tpu.utils.platform import axis_size as _axis_size
from distkeras_tpu.utils.platform import pcast as _pcast

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distkeras_tpu.parallel.pipeline import stack_stage_params  # noqa: F401

__all__ = ["pipeline_1f1b_value_and_grad", "ticks_1f1b"]


def ticks_1f1b(num_microbatches: int, num_devices: int) -> int:
    """Scan length: the last backward is B_0(M-1) at ``2(M-1) + 2P - 1``."""
    return 2 * num_microbatches + 2 * num_devices - 2


def _1f1b_local(
    stage_fn, last_fn, stacked_params, head_params, microbatches, labels,
    rng, axis_name: str, varying_axes=(), with_aux: bool = False,
    stage_aux_seed: float | None = None,
):
    """Per-device body (inside shard_map over ``axis_name`` + any dp axes).

    ``stage_aux_seed`` switches on the MoE mode: ``stage_fn`` returns
    ``(y, aux_raw)`` and ``last_fn`` returns ``(loss, aux_raw)`` (or
    ``(loss, aux_raw, metrics_aux)`` under ``with_aux``), where
    ``aux_raw`` is a differentiated auxiliary loss (the MoE load-balance
    term). Each backward tick's vjp seeds the aux output with the scalar
    ``stage_aux_seed`` (the caller folds its weight and 1/M there), so the
    optimized total is ``sum(loss) + seed * sum(aux_raw)`` while the
    returned loss value stays the pure task loss; the raw aux sum is
    accumulated separately for metrics. Expert-parallel stages (a psum
    over an ``ep`` mesh axis inside ``stage_fn``) are safe here: the
    branch predicates vary over ``axis_name`` only, every ep peer of a pp
    row takes the same branch, and activations/loss stay ep-INVARIANT
    (the forward psum removes the ep axis from the vma), so autodiff
    inserts only ep-psums inside branches — never the pp/dp psums that
    deadlock (the reason everything else is pcast varying below).
    """
    d = lax.axis_index(axis_name)
    num_devices = _axis_size(axis_name)
    M, B = microbatches.shape[0], microbatches.shape[1]
    feat = microbatches.shape[2:]
    dtype = microbatches.dtype
    Pd = num_devices
    all_axes = (axis_name, *varying_axes)
    moe = stage_aux_seed is not None

    my_params = jax.tree.map(lambda x: x[0], stacked_params)  # [1,...] shard
    fwd_perm = [(i, (i + 1) % Pd) for i in range(Pd)]
    bwd_perm = [(i, (i - 1) % Pd) for i in range(Pd)]

    def varying(x):
        # Pre-VMA jax has no jax.typeof/vma tracking; _pcast is identity
        # there, so "need everything" is both safe and correct.
        typeof = getattr(jax, "typeof", None)
        have = getattr(typeof(x), "vma", ()) if typeof is not None else ()
        need = tuple(a for a in all_axes if a not in have)
        return _pcast(x, need, to="varying") if need else x

    # CRITICAL: the head params must be varying before any vjp touches
    # them. Taking a cotangent w.r.t. an axis-INVARIANT input makes JAX
    # close the transpose with a psum over that axis — and here the vjp
    # runs inside a cond branch only the last pp row takes, so that psum
    # would be a collective inside a divergent branch: a lock-step
    # deadlock (observed as an XLA rendezvous timeout). Varying inputs
    # need no such psum; the reductions happen once, after the scan, on
    # the accumulated values.
    head_params = jax.tree.map(varying, head_params)
    my_params = jax.tree.map(varying, my_params)

    zero_state = jnp.zeros((B, *feat), dtype)
    carry0 = dict(
        act_in=varying(zero_state),            # activation arriving for F
        cot_in=varying(zero_state.astype(jnp.float32)),  # arriving cotangent
        ring=varying(jnp.zeros((Pd, B, *feat), dtype)),  # in-flight inputs
        grads=jax.tree.map(lambda x: varying(jnp.zeros_like(x)), my_params),
        head_grads=jax.tree.map(
            lambda x: varying(jnp.zeros_like(x, dtype=jnp.float32)),
            head_params,
        ),
        loss=varying(jnp.float32(0.0)),
        aux=varying(jnp.float32(0.0)),
        moe_aux=varying(jnp.float32(0.0)),
        cot_out=varying(jnp.zeros((M, B, *feat), jnp.float32)),
    )

    last = Pd - 1

    def key_for(m):
        # Deterministic per (microbatch, stage, dp-slice): the backward
        # recompute reproduces the forward's dropout masks exactly, and dp
        # replicas (different data shards) get independent masks.
        if rng is None:
            return None
        key = jax.random.fold_in(jax.random.fold_in(rng, m), d)
        for ax in varying_axes:
            key = jax.random.fold_in(key, lax.axis_index(ax))
        return key

    def apply_stage(p, x, m):
        # moe mode: returns (y, aux_raw); otherwise just y.
        if rng is None:
            return stage_fn(p, x)
        return stage_fn(p, x, key_for(m))

    def apply_last(p, hp, x, yl, m):
        # Normalized to ((loss, stage_aux), metrics_aux): the first pair is
        # differentiated (aux seeded with stage_aux_seed in moe mode), the
        # metrics channel rides has_aux.
        if rng is None:
            out = last_fn(p, hp, x, yl)
        else:
            out = last_fn(p, hp, x, yl, key_for(m))
        if moe:
            if with_aux:
                loss, saux, maux = out
            else:
                (loss, saux), maux = out, jnp.float32(0.0)
            return (loss, saux), maux
        if with_aux:
            loss, maux = out
        else:
            loss, maux = out, jnp.float32(0.0)
        return (loss, jnp.float32(0.0)), maux

    def make_tick(enable_f: bool, enable_b: bool):
        """One scan body, specialized to the phase (static at trace time):

        - fill  (ticks 0..P-1):       no backward exists anywhere (the
          earliest B tick is 2P-1-(P-1) = P), so the cotangent ppermute is
          statically dead — elide it, and compile no b_branch at all;
        - steady (ticks P..2M+P-3):   both hops, the full 3-way switch;
        - drain (ticks 2M+P-2..T-1):  no forward exists anywhere (the
          latest F tick is 2(M-1)+P-1 = 2M+P-3) and the activation sent at
          2M+P-3 is never consumed, so the activation ppermute is
          statically dead — elide it, and compile no f_branch.

        This removes P full-size hops per direction per step (all of the
        fill phase's cotangent traffic and the drain phase's activation
        traffic — VERDICT r4 weak #5) and shrinks the fill/drain scan
        bodies to single-role conds.
        """
        assert enable_f or enable_b

        return partial(_tick, enable_f, enable_b)

    def _tick(enable_f, enable_b, carry, t):
        # Role this tick (mutually exclusive by parity — see module doc).
        mf2, mb2 = t - d, t - (2 * Pd - 1 - d)
        is_f = (mf2 >= 0) & (mf2 % 2 == 0) & (mf2 // 2 < M)
        is_b = (mb2 >= 0) & (mb2 % 2 == 0) & (mb2 // 2 < M)
        m_f = jnp.clip(mf2 // 2, 0, M - 1)
        m_b = jnp.clip(mb2 // 2, 0, M - 1)

        def f_branch(c):
            x_feed = lax.dynamic_index_in_dim(microbatches, m_f, 0, False)
            x = jnp.where(d == 0, x_feed, c["act_in"])
            ring = lax.dynamic_update_index_in_dim(c["ring"], x, m_f % Pd, 0)
            # The last device's F output is never consumed (its B tick
            # recomputes through the vjp) — genuinely skip the stage math
            # there via cond (a where would still evaluate apply_stage,
            # charging the last stage one discarded forward per
            # microbatch). Safe: stage_fn is collective-free over pp/dp
            # under the 1F1B constraints, so branch divergence across pp
            # rows cannot deadlock.
            if moe:
                def run_stage(xx):
                    yy, _aux = apply_stage(my_params, xx, m_f)
                    return yy  # aux is accounted once, at the B-tick recompute
            else:
                def run_stage(xx):
                    return apply_stage(my_params, xx, m_f)
            y = lax.cond(
                d == last,
                lambda xx: varying(jnp.zeros_like(xx)),
                run_stage,
                x,
            )
            return (
                dict(c, ring=ring), y,
                varying(jnp.zeros((B, *feat), jnp.float32)),
            )

        def b_branch(c):
            x = lax.dynamic_index_in_dim(c["ring"], m_b % Pd, 0, False)

            def last_loss(p, hp, xx):
                # ((loss, stage_aux), metrics_aux) — see apply_last.
                yl = lax.dynamic_index_in_dim(labels, m_b, 0, False)
                return apply_last(p, hp, xx, yl, m_b)

            def mid_apply(p, xx):
                return apply_stage(p, xx, m_b)

            def do_last(_):
                (loss_m, saux_m), vjp, aux_m = jax.vjp(
                    last_loss, my_params, head_params, x, has_aux=True
                )
                if moe:
                    # Seed the aux-loss output with the caller's weight so
                    # its gradient (router load balance) flows alongside the
                    # task loss through the SAME recompute.
                    gp, ghp, gx = vjp((
                        jnp.ones_like(loss_m),
                        jnp.full_like(saux_m, stage_aux_seed),
                    ))
                else:
                    gp, ghp, gx = vjp((
                        jnp.ones_like(loss_m), jnp.zeros_like(saux_m)
                    ))
                # f32 accumulators regardless of head param dtype.
                ghp = jax.tree.map(lambda g: g.astype(jnp.float32), ghp)
                return (
                    loss_m.astype(jnp.float32),
                    # with_aux=False feeds a fresh (invariant) zero here;
                    # match the other branch's varying type.
                    varying(aux_m.astype(jnp.float32)),
                    varying(saux_m.astype(jnp.float32)),
                    gp, ghp, gx.astype(jnp.float32),
                )

            def do_mid(_):
                if moe:
                    (_, saux_m), vjp = jax.vjp(mid_apply, my_params, x)
                    gp, gx = vjp((
                        c["cot_in"].astype(dtype),
                        jnp.full_like(saux_m, stage_aux_seed),
                    ))
                else:
                    _, vjp = jax.vjp(mid_apply, my_params, x)
                    gp, gx = vjp(c["cot_in"].astype(dtype))
                    saux_m = jnp.float32(0.0)
                # Fresh zeros are axis-invariant; the cond's other branch
                # returns varying values — match the types explicitly.
                return (
                    varying(jnp.float32(0.0)), varying(jnp.float32(0.0)),
                    varying(saux_m.astype(jnp.float32)),
                    gp,
                    jax.tree.map(
                        lambda z: varying(jnp.zeros_like(z)),
                        c["head_grads"],
                    ),
                    gx.astype(jnp.float32),
                )

            loss_m, aux_m, saux_m, gp, ghp, gx = lax.cond(
                d == last, do_last, do_mid, None
            )
            grads = jax.tree.map(jnp.add, c["grads"], gp)
            head_grads = jax.tree.map(jnp.add, c["head_grads"], ghp)
            # Device 0's input cotangent feeds the embedding backward.
            cot_out = jnp.where(
                d == 0,
                lax.dynamic_update_index_in_dim(c["cot_out"], gx, m_b, 0),
                c["cot_out"],
            )
            return (
                dict(c, grads=grads, head_grads=head_grads,
                     loss=c["loss"] + loss_m, aux=c["aux"] + aux_m,
                     moe_aux=c["moe_aux"] + saux_m,
                     cot_out=cot_out),
                varying(jnp.zeros((B, *feat), dtype)),
                gx,
            )

        def idle(c):
            return (
                c,
                varying(jnp.zeros((B, *feat), dtype)),
                varying(jnp.zeros((B, *feat), jnp.float32)),
            )

        if enable_f and enable_b:
            role = jnp.where(is_f, 1, jnp.where(is_b, 2, 0))
            carry, y_send, cot_send = lax.switch(
                role, [idle, f_branch, b_branch], carry
            )
        elif enable_f:
            carry, y_send, cot_send = lax.cond(is_f, f_branch, idle, carry)
        else:
            carry, y_send, cot_send = lax.cond(is_b, b_branch, idle, carry)
        # Collectives run unconditionally (outside the switch) on every
        # tick of their phase — lock-step across pp rows by construction.
        updates = {}
        if enable_f:
            updates["act_in"] = lax.ppermute(y_send, axis_name, fwd_perm)
        if enable_b:
            updates["cot_in"] = lax.ppermute(
                cot_send.astype(jnp.float32), axis_name, bwd_perm
            )
        return dict(carry, **updates), None

    # Three statically-specialized phases (see make_tick): boundaries from
    # the tick algebra — B ticks live in [P, 2M+2P-3], F in [0, 2M+P-3].
    T = ticks_1f1b(M, Pd)
    fill_end = min(Pd, T)
    drain_start = max(2 * M + Pd - 2, fill_end)
    carry, _ = lax.scan(make_tick(True, False), carry0, jnp.arange(fill_end))
    carry, _ = lax.scan(
        make_tick(True, True), carry, jnp.arange(fill_end, drain_start)
    )
    carry, _ = lax.scan(
        make_tick(False, True), carry, jnp.arange(drain_start, T)
    )
    # Disjoint sums over pp (loss/aux/head_grads live on the last pp row,
    # cot_out on row 0); means over any dp axes — the mean-loss convention
    # (each dp slice computed its shard's mean loss).
    loss = lax.psum(carry["loss"], axis_name)
    aux = lax.psum(carry["aux"], axis_name)
    # Per-stage aux losses live disjointly on their own pp rows (each stage
    # accumulated its own layers' aux at its B ticks) — a psum collects.
    moe_aux = lax.psum(carry["moe_aux"], axis_name)
    head_grads = jax.tree.map(
        lambda g: lax.psum(g, axis_name), carry["head_grads"]
    )
    stage_grads = carry["grads"]
    for ax in varying_axes:
        loss = lax.pmean(loss, ax)
        aux = lax.pmean(aux, ax)
        moe_aux = lax.pmean(moe_aux, ax)
        head_grads = jax.tree.map(lambda g: lax.pmean(g, ax), head_grads)
        stage_grads = jax.tree.map(lambda g: lax.pmean(g, ax), stage_grads)
    cot_out = lax.psum(carry["cot_out"], axis_name)
    # The cotangents must match the mean-loss convention of the pmean-ed
    # grads above: each dp slice computed the cotangent of ITS shard-mean
    # loss, and the global loss is the pmean — scale by 1/dp so the
    # caller's embedding vjp lands gradients on the same scale as the
    # stage/head grads (they stay dp-sharded like the inputs).
    for ax in varying_axes:
        cot_out = cot_out / _axis_size(ax)
    stage_grads = jax.tree.map(lambda g: g[None], stage_grads)
    out = (loss,)
    if with_aux:
        out += (aux,)
    if moe:
        out += (moe_aux,)
    return out + (stage_grads, head_grads, cot_out)


def pipeline_1f1b_value_and_grad(
    stage_fn,
    last_fn,
    stacked_params,
    head_params,
    microbatches,
    labels,
    mesh: Mesh,
    axis_name: str = "pp",
    rng=None,
    with_aux: bool = False,
    io_spec: P | None = None,
    param_specs=None,
    stage_aux_seed: float | None = None,
):
    """Run one 1F1B train-step evaluation over ``mesh[axis_name]``.

    - ``stage_fn(stage_params, x) -> y`` (``stage_fn(p, x, key)`` when
      ``rng`` is given) with ``y.shape == x.shape`` — applied by devices
      ``0 .. P-2`` and recomputed inside the last device's vjp;
    - ``last_fn(stage_params, head_params, x, labels_mb) -> scalar loss``
      (``(loss, aux_scalar)`` when ``with_aux``; extra ``key`` arg when
      ``rng`` is given) — the last stage *including head and loss* for
      one microbatch;
    - ``stacked_params``: PyTree with leading stage axis ``[P, ...]``
      (:func:`stack_stage_params`), sharded over ``axis_name``;
    - ``head_params``: replicated head/loss params;
    - ``microbatches``: ``[M, B, ...]``; ``labels``: ``[M, ...]``. By
      default replicated; pass ``io_spec`` (e.g. ``P(None, "dp")``) to
      shard the batch axis over dp — each dp slice runs its own pipe and
      losses/grads are pmean-ed (mean-loss convention).

    With ``stage_aux_seed`` (MoE mode): ``stage_fn`` returns
    ``(y, aux_raw)`` and ``last_fn`` returns ``(loss, aux_raw)`` (plus the
    metrics channel under ``with_aux``); every backward vjp seeds the aux
    output with ``stage_aux_seed`` so the optimized total is
    ``sum(loss) + seed*sum(aux_raw)``, and the raw aux sum is returned for
    metrics. Pass ``param_specs`` to shard expert-weight leaves
    ``P(axis_name, "ep")`` for expert parallelism inside the pipe (the
    stage fn runs the MoE block in manual-collective mode and psums over
    ``ep``; see the module docstring on why that composes safely with the
    divergent tick branches).

    Returns ``(loss_sum[, aux_sum][, moe_aux_sum], stage_grads,
    head_grads, input_cotangents)``: the summed microbatch losses (and
    aux channels), gradients stacked ``[P, ...]`` over the stage axis
    (expert leaves keep their ``param_specs`` sharding), head gradients,
    and ``[M, B, ...]`` input cotangents (float32, sharded like the
    inputs) for the caller's embedding backward. Divide by ``M`` for
    means. Saved stage activations are O(P) microbatch states (ring
    buffer); total residency adds one M-sized input-cotangent buffer —
    ``(min(P, M) + M)`` states, see the module docstring.
    """
    from distkeras_tpu.utils.platform import get_shard_map

    shard_map = get_shard_map()

    if io_spec is None:
        io_spec = P()
    varying_axes = tuple(
        ax
        for entry in io_spec
        if entry is not None
        for ax in ((entry,) if isinstance(entry, str) else tuple(entry))
        if ax != axis_name
    )
    # param_specs carries expert-parallel shardings (e.g. P("pp", "ep") on
    # MoE expert-weight leaves): each pp row's ep group holds a slice of
    # that stage's experts, and the returned gradients come back with the
    # SAME specs (expert grads stay ep-sharded — they are exact local
    # grads, no cross-ep reduction exists for disjoint expert slices).
    spec_p = (
        param_specs
        if param_specs is not None
        else jax.tree.map(lambda _: P(axis_name), stacked_params)
    )
    n_out = 4 + int(with_aux) + int(stage_aux_seed is not None)
    out_specs = (
        (P(),) * (n_out - 3)
        + (
            spec_p,
            jax.tree.map(lambda _: P(), head_params),
            io_spec,
        )
    )
    fn = shard_map(
        partial(
            _1f1b_local, stage_fn, last_fn, axis_name=axis_name,
            varying_axes=varying_axes, with_aux=with_aux,
            stage_aux_seed=stage_aux_seed,
        ),
        mesh=mesh,
        in_specs=(spec_p, P(), io_spec, io_spec, P()),
        out_specs=out_specs,
    )
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead != mesh.shape[axis_name]:
        raise ValueError(
            f"stacked params have {lead} stages but mesh {axis_name}="
            f"{mesh.shape[axis_name]} (1F1B is non-interleaved: V=1)"
        )
    return fn(stacked_params, head_params, microbatches, labels, rng)
