"""GSPMD training: one model definition, any mesh.

This is the data+model-sharding path BASELINE config #5 requires
("BERT-base MLM via DynSGD with GSPMD data+model sharding") and the engine
behind ``SynchronousDistributedTrainer`` when the mesh has model axes:
parameters are laid out according to their logical-axis annotations
(:mod:`distkeras_tpu.parallel.sharding`), the batch is sharded over
``dp`` (and the sequence over ``sp`` when present), and every collective —
gradient psum over ``dp``, activation all-reduces over ``tp`` — is inserted
by XLA from the sharding constraints. No hand-written collectives in the
step function.
"""

from __future__ import annotations

from collections.abc import Callable

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.models.core import Model
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.parallel.sharding import infer_variable_shardings
from distkeras_tpu.training.step import TrainState

__all__ = [
    "sharded_train_state",
    "make_sharded_train_step",
    "batch_sharding",
    "place_sharded",
    "shard_batch",
]


def place_sharded(tree, shardings):
    """Shard-then-place: ``device_put`` every host leaf **directly into
    its mesh layout** — each device receives only its own slice of each
    leaf, never a full replicated copy that is then resharded.

    This is the cross-replica-sharding move of *Automatic Cross-Replica
    Sharding of Weight Update* (arXiv:2004.13336) applied to weight
    placement: for a rollout of new weights onto a ``tp``-sharded
    serving replica the host→device traffic is ``bytes/tp`` per device
    (bandwidth-optimal) instead of ``bytes`` per device, and no device
    ever has to hold a whole-model replica it immediately throws away.
    The serving engine's boot and hot-swap paths and the deploy
    harness's canary scoring all place through this one seam.
    ``shardings=None`` keeps the unsharded single-device behavior."""
    if shardings is None:
        return jax.device_put(tree)
    return jax.device_put(tree, shardings)


def batch_sharding(mesh: Mesh, batch_rank: int = 2, seq_dim: int | None = 1):
    """Sharding for a ``[B, ...]`` batch: B over the data axes (dp and, when
    present, fsdp — both carry data parallelism; policy lives in
    ``mesh.shard_batch_spec``), seq dim over sp."""
    from distkeras_tpu.parallel.mesh import shard_batch_spec

    batch_spec = shard_batch_spec(mesh)  # P(<data axes>) or P()
    spec: list = [None] * batch_rank
    if len(batch_spec) > 0:
        spec[0] = batch_spec[0]
    if seq_dim is not None and "sp" in mesh.axis_names and seq_dim < batch_rank:
        spec[seq_dim] = "sp"
    return NamedSharding(mesh, P(*spec))


def shard_batch(mesh: Mesh, batch: dict, seq_dim: int | None = None) -> dict:
    """device_put every array in ``batch`` with a rank-matched batch
    sharding (features may be [B, ...] of any rank; labels are often [B])."""
    return {
        k: jax.device_put(
            v, batch_sharding(mesh, max(1, np.ndim(v)), seq_dim=seq_dim)
        )
        for k, v in batch.items()
    }


def fsdp_sharding_for(
    mesh: Mesh, shape: tuple[int, ...], dtype=None, axis: str = "fsdp"
) -> NamedSharding:
    """Largest-divisible-dimension sharding heuristic for an un-annotated
    tensor over ``axis``; replicate otherwise. Small tensors (< 2^14
    elements) stay replicated — the all-gather would cost more than the
    memory saved."""
    if axis not in mesh.axis_names:
        return NamedSharding(mesh, P())
    n = mesh.shape[axis]
    if int(np.prod(shape or (1,))) < (1 << 14):
        return NamedSharding(mesh, P())
    dims = sorted(range(len(shape)), key=lambda i: -shape[i])
    for d in dims:
        if shape[d] % n == 0:
            spec = [None] * len(shape)
            spec[d] = axis
            return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def sharded_train_state(
    model: Model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rng: int | jax.Array = 0,
    zero1: bool = False,
):
    """Initialize a TrainState with every parameter placed per its logical
    axes — parameters materialize directly in their distributed layout
    (never whole on one device). Un-annotated models on an ``fsdp`` mesh get
    the heuristic of :func:`fsdp_sharding_for` (ZeRO-3-style: params live
    sharded; XLA all-gathers each layer's weights just-in-time and
    reduce-scatters its gradients)."""
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    # Same key split as TrainState.create so a sharded and an unsharded
    # init from the same seed produce identical parameters.
    init_rng, step_rng = jax.random.split(rng)
    rng = init_rng
    boxed_init = getattr(model, "boxed_init", None)

    # The init COMPUTATION runs without output-sharding constraints, and
    # the result is then device_put into the target layout. Jitting the
    # init with TP/FSDP out_shardings lets GSPMD propagate the sharding
    # INTO the (legacy, non-partitionable) threefry ops, which CHANGES
    # the generated values — a tp-sharded kernel would initialize
    # differently from the same seed's unsharded init, silently breaking
    # the contract above (observed on jax 0.4.37: bert-TP kernels off by
    # ~0.27 absolute). jax_threefry_partitionable=True would make the
    # sharded lowering value-invariant but changes the stream relative
    # to today's eager inits, breaking every same-seed baseline — so the
    # fix is to keep the random bits unsharded and reshard the DATA. The
    # cost is one full materialization at init time before the
    # device_put redistributes; at the scale where even that overflows a
    # device, flip the global partitionable flag instead and re-seed.
    if boxed_init is not None:
        abstract = jax.eval_shape(boxed_init, rng)
        var_shardings = infer_variable_shardings(mesh, abstract)

        def init_fn(r):
            from flax import linen as nn

            return nn.meta.unbox(boxed_init(r))

        variables = jax.device_put(jax.jit(init_fn)(rng), var_shardings)
    elif "fsdp" in mesh.axis_names and mesh.shape["fsdp"] > 1:
        abstract = jax.eval_shape(model.init, rng)
        var_shardings = jax.tree.map(
            lambda a: fsdp_sharding_for(mesh, a.shape, a.dtype), abstract
        )
        variables = jax.device_put(jax.jit(model.init)(rng), var_shardings)
    else:
        # Un-annotated model: replicate everything (pure DP).
        replicated = NamedSharding(mesh, P())
        variables = jax.jit(model.init, out_shardings=replicated)(rng)
        var_shardings = jax.tree.map(lambda _: replicated, variables)

    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}
    param_shardings = var_shardings["params"]
    if zero1 and "dp" in mesh.axis_names and mesh.shape["dp"] > 1:
        # ZeRO-1 / cross-replica weight-update sharding (PAPERS.md:
        # arXiv:2004.13336): optimizer moments shard over the data axis even
        # when params are replicated — XLA reduce-scatters gradients into
        # the moment shards and all-gathers the updates.
        abstract_opt = jax.eval_shape(optimizer.init, params)
        opt_shardings = jax.tree.map(
            lambda a: fsdp_sharding_for(mesh, a.shape, axis="dp"), abstract_opt
        )
        opt_state = jax.jit(
            optimizer.init,
            in_shardings=(param_shardings,),
            out_shardings=opt_shardings,
        )(params)
    else:
        opt_state = jax.jit(
            optimizer.init, in_shardings=(param_shardings,), out_shardings=None
        )(params)
    state = TrainState(
        params=params,
        model_state=model_state,
        opt_state=opt_state,
        step=jax.device_put(np.int32(0), NamedSharding(mesh, P())),
        rng=jax.device_put(step_rng, NamedSharding(mesh, P())),
    )
    return state, var_shardings


def make_sharded_train_step(
    model: Model,
    optimizer: optax.GradientTransformation,
    loss: str | Callable,
    mesh: Mesh,
    donate: bool = True,
    metrics: tuple[str, ...] = ("accuracy",),
    aux_loss_weight: float = 0.01,
):
    """Jitted ``(state, batch) -> (state, metrics)`` under GSPMD.

    The step body is identical to the single-chip engine — shardings on the
    inputs are the only distribution mechanism. XLA turns the params'
    layouts into tp collectives and the batch layout into a dp gradient
    all-reduce over ICI.
    """
    loss_fn = get_loss(loss)

    def step(state: TrainState, batch: dict):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def compute_loss(params):
            variables = {"params": params, **state.model_state}
            outputs, new_model_state = model.apply(
                variables, batch["features"], train=True, rngs={"dropout": step_rng}
            )
            from distkeras_tpu.training.step import apply_aux_loss

            task_loss, new_model_state = apply_aux_loss(
                loss_fn(outputs, batch["label"]), new_model_state,
                aux_loss_weight,
            )
            return task_loss, (outputs, new_model_state)

        (loss_value, (outputs, new_model_state)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            params=new_params,
            model_state=new_model_state if new_model_state else state.model_state,
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        out_metrics = {"loss": loss_value}
        if "accuracy" in metrics:
            from distkeras_tpu.ops.metrics import accuracy as accuracy_metric

            out_metrics["accuracy"] = accuracy_metric(outputs, batch["label"])
        return new_state, out_metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())
