"""Remote job deployment — parity with ``distkeras/job_deployment.py``.

The reference's (explicitly experimental) ``Job`` scp's a data file and a
training script to a cluster head node, runs ``spark-submit`` over SSH, and
fetches artifacts back; ``Punchcard`` batches such jobs from a JSON spec
with credentials. The TPU equivalent keeps the same surface but targets a
TPU host (or any ssh-reachable machine with the framework installed):

- ``Job``: copy inputs, run ``python <script>`` remotely (optionally under a
  process-group rendezvous, see :mod:`distkeras_tpu.parallel.distributed`),
  fetch the output directory.
- ``Punchcard``: read a JSON list of job specs and run them sequentially.

Like the reference, this shells out to ``ssh``/``scp``; with ``host=None``
it degrades to running the script locally, which is also how it is tested
in this container (no egress).
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
from typing import Any

__all__ = ["Job", "Punchcard"]


class Job:
    """One remote training job (reference ``job_deployment.py`` § ``Job``).

    Parameters mirror the reference: job name, address of the target
    machine, username, paths of the data and script to ship, and where
    results land.
    """

    def __init__(
        self,
        job_name: str,
        address: str | None,
        username: str | None = None,
        data_path: str | None = None,
        script_path: str | None = None,
        remote_dir: str = "~/distkeras_jobs",
        fetch: tuple[str, ...] = (),
        env: dict[str, str] | None = None,
    ):
        self.job_name = job_name
        self.address = address
        self.username = username
        self.data_path = data_path
        self.script_path = script_path
        self.remote_dir = remote_dir
        self.fetch = tuple(fetch)
        self.env = dict(env or {})
        self.returncode: int | None = None
        self.output: str = ""

    # -- internals -----------------------------------------------------------

    def _target(self) -> str:
        return f"{self.username}@{self.address}" if self.username else self.address

    def _run(self, argv: list[str]) -> subprocess.CompletedProcess:
        return subprocess.run(argv, capture_output=True, text=True)

    def _remote_job_dir(self) -> str:
        return f"{self.remote_dir}/{self.job_name}"

    # -- lifecycle (reference: send -> execute -> fetch) ---------------------

    def send(self) -> None:
        """Ship data and script to the target (scp), or stage locally (same
        layout the remote path establishes: inputs sit next to the script)."""
        if self.address is None:
            import shutil

            os.makedirs(self._local_dir(), exist_ok=True)
            for p in filter(None, (self.data_path, self.script_path)):
                # raise the real error here, not a confusing missing-file
                # failure later in execute()
                if os.path.isdir(p):
                    shutil.copytree(
                        p,
                        os.path.join(self._local_dir(), os.path.basename(p)),
                        dirs_exist_ok=True,
                    )
                else:
                    shutil.copy2(p, self._local_dir())
            return
        self._run(["ssh", self._target(), f"mkdir -p {self._remote_job_dir()}"])
        for p in filter(None, (self.data_path, self.script_path)):
            r = self._run(["scp", "-q", p, f"{self._target()}:{self._remote_job_dir()}/"])
            if r.returncode != 0:
                raise RuntimeError(f"scp failed for {p}: {r.stderr.strip()}")

    def _local_dir(self) -> str:
        return os.path.expanduser(f"{self.remote_dir}/{self.job_name}".replace("~", os.path.expanduser("~")))

    def execute(self) -> int:
        """Run the script (remotely over ssh, or locally with address=None)."""
        if self.script_path is None:
            raise ValueError("Job has no script_path")
        env_prefix = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in self.env.items()
        )
        script_name = os.path.basename(self.script_path)
        if self.address is None:
            # run the staged copy by name, mirroring the remote layout
            cmd = (
                f"cd {shlex.quote(self._local_dir())} && {env_prefix} "
                f"python {shlex.quote(script_name)}"
            )
            r = subprocess.run(["bash", "-c", cmd], capture_output=True, text=True)
        else:
            remote_cmd = (
                f"cd {self._remote_job_dir()} && {env_prefix} python {script_name}"
            )
            r = self._run(["ssh", self._target(), remote_cmd])
        self.returncode = r.returncode
        self.output = r.stdout + r.stderr
        return self.returncode

    def fetch_artifacts(self, local_dir: str) -> list[str]:
        os.makedirs(local_dir, exist_ok=True)
        fetched = []
        for name in self.fetch:
            if self.address is None:
                src = os.path.join(self._local_dir(), name)
                if os.path.exists(src):
                    dst = os.path.join(local_dir, name)
                    subprocess.run(["cp", "-r", src, dst], check=False)
                    fetched.append(dst)
            else:
                dst = os.path.join(local_dir, name)
                r = self._run(
                    ["scp", "-rq", f"{self._target()}:{self._remote_job_dir()}/{name}", dst]
                )
                if r.returncode == 0:
                    fetched.append(dst)
        return fetched

    def run(self, local_artifact_dir: str | None = None) -> int:
        """send -> execute -> fetch, returning the exit code."""
        self.send()
        code = self.execute()
        if local_artifact_dir:
            self.fetch_artifacts(local_artifact_dir)
        return code


class Punchcard:
    """Batch job runner from a JSON spec file (reference
    ``job_deployment.py`` § ``Punchcard``).

    Spec format: ``{"jobs": [{"job_name": ..., "address": ...,
    "script_path": ..., ...}, ...]}`` — keys are :class:`Job` kwargs.
    """

    def __init__(self, path: str):
        with open(path) as f:
            self.spec: dict[str, Any] = json.load(f)
        if "jobs" not in self.spec or not isinstance(self.spec["jobs"], list):
            raise ValueError("punchcard spec needs a top-level 'jobs' list")
        self.jobs = [Job(**j) for j in self.spec["jobs"]]

    def run(self, stop_on_failure: bool = True) -> list[int]:
        codes = []
        for job in self.jobs:
            codes.append(job.run())
            if codes[-1] != 0 and stop_on_failure:
                break
        return codes
