"""Unified observability layer: spans, recompile auditing, metrics.

The reference system's observability was wall-clock getters plus the
Spark web UI (SURVEY §5); through PR 1 this repo had grown two disjoint
islands — ``tracing.py`` (training step timers / metric streams) and
``serving/metrics.py`` (latency percentiles). This package is the single
layer both sides publish into. Four pillars:

- **spans** (:mod:`.spans`) — hierarchical host-timeline spans
  (``with span("decode_tick"): ...``) with thread/task-correct parent
  tracking, near-zero overhead when disabled, exported as Chrome-trace
  JSON that Perfetto renders as one timeline per run;
- **recompile auditing** (:mod:`.recompile`) — wrap jitted callables,
  count compiles with the triggering abstract shapes, and arm after
  warmup so a silent retrace becomes a loud :class:`RecompileError`;
- **metrics registry** (:mod:`.registry`) — counter/gauge/histogram
  get-or-create registry every subsystem publishes into, with the ONE
  shared :func:`percentile` definition;
- **exposition** (:mod:`.exposition`) — Prometheus text + JSONL
  snapshots; the serving server serves both via its ``metricsz`` control
  verb, ``run.py`` wires ``--trace-out`` / ``--audit-recompiles``;
- **fleet timeseries** (:mod:`.timeseries`) — the push-plane half:
  registry delta encoding for replica→router telemetry pushes, the
  router-side fold into fleet-merged histograms (bucket-exact fleet
  p99s), and a ring-buffer store of per-window aggregates the SLO
  burn-rate engine queries by metric name and span;
- **request tracing** (:mod:`.request_trace`) — per-request trace ids
  propagated across the serving cluster's processes, per-hop timeline
  records, bounded stores behind the ``tracez`` control verb, and
  one-lane-per-request Chrome export;
- **wide events** (:mod:`.wide_events`) — one canonical flat record
  per finished request in a bounded columnar ring, with a filter /
  group-by / aggregate query engine behind the ``queryz`` verb whose
  percentile aggregates merge bucket-exactly across the fleet;
- **flight recorder** (:mod:`.flight_recorder`) — bounded overwrite
  rings of recent state transitions + request timelines, dumped as a
  replica's "last words" on crash and mined for slow-request exemplars;
- **training health** (:mod:`.training_health`) — the training-side
  peer of the serving stack: per-worker commit staleness histograms
  with exemplars, EASGD center-divergence gauges, goodput (effective
  vs staleness-damped update mass), and the ``statusz`` snapshot
  ``run.py --statusz-out`` writes live;
- **device accounting** (:mod:`.device`) — ``memory_stats()`` probes
  behind a typed "unavailable" sentinel, per-device memory gauges, and
  the promoted ``jax.profiler`` capture (``--profile-out``).
"""

from distkeras_tpu.telemetry.spans import (
    Tracer,
    active_tracer,
    disable_tracing,
    enable_tracing,
    span,
)
from distkeras_tpu.telemetry.recompile import (
    CompileEvent,
    RecompileAuditor,
    RecompileError,
    abstract_signature,
)
from distkeras_tpu.telemetry.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    hist_state_delta,
    hist_state_percentile,
    log_buckets,
    merge_hist_states,
    percentile,
    sanitize_metric_name,
)
from distkeras_tpu.telemetry.timeseries import (
    DeltaEncoder,
    FleetAggregator,
    TimeSeriesStore,
)
from distkeras_tpu.telemetry.exposition import (
    prometheus_text,
    write_snapshot_jsonl,
)
from distkeras_tpu.telemetry.request_trace import (
    TailRetention,
    TimelineRecord,
    TraceStore,
    chrome_trace,
    merge_trace,
    new_trace_id,
)
from distkeras_tpu.telemetry.wide_events import (
    WideEventStore,
    merge_query_results,
)
from distkeras_tpu.telemetry.flight_recorder import (
    FlightRecorder,
    load_flight_dump,
)
from distkeras_tpu.telemetry.training_health import (
    STALENESS_BUCKETS,
    TrainingHealth,
)
from distkeras_tpu.telemetry.device import (
    DeviceMemory,
    all_device_memory,
    device_memory,
    profile_trace,
    publish_memory_gauges,
)

__all__ = [
    "Tracer",
    "span",
    "enable_tracing",
    "disable_tracing",
    "active_tracer",
    "RecompileAuditor",
    "RecompileError",
    "CompileEvent",
    "abstract_signature",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "percentile",
    "sanitize_metric_name",
    "DEFAULT_BUCKETS",
    "log_buckets",
    "hist_state_delta",
    "hist_state_percentile",
    "merge_hist_states",
    "DeltaEncoder",
    "TimeSeriesStore",
    "FleetAggregator",
    "prometheus_text",
    "write_snapshot_jsonl",
    "new_trace_id",
    "TimelineRecord",
    "TailRetention",
    "TraceStore",
    "WideEventStore",
    "merge_query_results",
    "merge_trace",
    "chrome_trace",
    "FlightRecorder",
    "load_flight_dump",
    "TrainingHealth",
    "STALENESS_BUCKETS",
    "DeviceMemory",
    "device_memory",
    "all_device_memory",
    "publish_memory_gauges",
    "profile_trace",
]
