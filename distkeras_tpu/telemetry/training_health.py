"""Async-training health: staleness, divergence, goodput, per-worker vitals.

The serving side can answer "is the server healthy?" in exquisite detail
(metricsz/healthz/debugz, request timelines, the flight recorder); this
module is the training-side peer, built around what makes ASYNC
data-parallel training succeed or silently rot:

- **staleness** — how far behind the PS counter each commit's pull was
  (``num_updates - last_update``, the exact quantity DynSGD damps by).
  Tracked as per-worker and global histograms with worst-sample
  exemplars (the worker that produced the stalest commit in each
  bucket), plus exact sliding-window percentiles for statusz;
- **divergence** — how far the workers have drifted from the center:
  the elastic family's ``||local - center||_2`` per exchange (EASGD's
  own control signal), and a global update-norm histogram for the
  delta family;
- **goodput** — effective vs damped update mass: the L2 mass workers
  computed (``update_mass``) vs what the protocol actually applied
  after staleness damping / 1-over-N normalization (``applied_mass``).
  A goodput ratio sliding toward zero means the fleet is doing work
  the protocol is throwing away — the "tune the exchange interval"
  signal DeepSpark/SparkNet center on;
- **per-worker vitals** — commit/pull/duplicate/rebase counts,
  last-commit age (a wedged worker shows up as one growing age, not a
  slightly-lower aggregate rate), and commit rate.

One :class:`TrainingHealth` is shared by the PS loop (which calls
:meth:`observe_commit` with each protocol's
:meth:`~distkeras_tpu.parallel.protocols.AsyncProtocol.commit_stats`)
and the worker threads (pulls, window completions, rebases). All
methods are thread-safe and **never raise into the caller** — telemetry
must not take down training. :meth:`statusz` renders the whole picture
as a JSON-able snapshot (``run.py`` writes it live via
``--statusz-out``; :func:`distkeras_tpu.serving.debugz.format_statusz`
pretty-prints it), and every series also publishes into an optional
:class:`~distkeras_tpu.telemetry.registry.MetricsRegistry`.
"""

from __future__ import annotations

import collections
import logging
import threading
import time

from distkeras_tpu.telemetry.registry import MetricsRegistry, percentile

__all__ = ["TrainingHealth", "STALENESS_BUCKETS"]

# Integer staleness in commits: 0 = perfectly fresh. Upper bounds chosen
# so a healthy run (staleness ~ num_workers) sits in the low buckets and
# anything past 64 is already pathological.
STALENESS_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256)

# Update-norm magnitudes span model scales; wide log buckets.
_NORM_BUCKETS = (1e-4, 1e-3, 1e-2, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0,
                 100.0, 1000.0)


class _WorkerStats:
    """Mutable per-worker record (guarded by TrainingHealth's lock)."""

    __slots__ = ("commits", "duplicates", "pulls", "rebases", "windows",
                 "steps", "last_commit_t", "last_staleness", "staleness",
                 "commit_times", "last_divergence")

    def __init__(self, window: int):
        self.commits = 0
        self.duplicates = 0
        self.pulls = 0
        self.rebases = 0
        self.windows = 0
        self.steps = 0
        self.last_commit_t: float | None = None
        self.last_staleness: int | None = None
        self.last_divergence: float | None = None
        self.staleness: collections.deque = collections.deque(maxlen=window)
        self.commit_times: collections.deque = collections.deque(maxlen=256)


class TrainingHealth:
    """Aggregates async-protocol health; see the module docstring.

    ``registry=None`` keeps everything in-process (statusz still works);
    with a registry, the histograms/counters/gauges below are published
    under ``train_*`` names. ``window`` bounds the exact-percentile
    sliding windows (the registry histograms are O(buckets) regardless).
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 num_workers: int = 0, protocol: str = "",
                 window: int = 1024):
        self.registry = registry
        self.num_workers = int(num_workers)
        self.protocol = str(protocol)
        self._lock = threading.Lock()
        self._window = int(window)
        self._workers: dict = {}
        self._staleness: collections.deque = collections.deque(
            maxlen=4 * self._window)
        self._update_norms: collections.deque = collections.deque(
            maxlen=4 * self._window)
        self._update_mass = 0.0
        self._applied_mass = 0.0
        self._divergence: float | None = None
        self._errors = 0
        self._t0 = time.time()
        self._ps = None  # ParameterServerService, for queue/counter rollup
        self._params_bytes: int | None = None

        self._h_staleness = self._h_norm = self._h_divergence = None
        self._c_commits = self._c_dups = self._c_rebases = None
        self._c_pulls = self._c_mass = self._c_applied = None
        self._g_goodput = self._g_divergence = None
        if registry is not None:
            self._h_staleness = registry.histogram(
                "train_commit_staleness",
                help="PS-counter lag of each commit's pull "
                     "(num_updates - last_update)",
                buckets=STALENESS_BUCKETS)
            self._h_norm = registry.histogram(
                "train_update_norm",
                help="L2 norm of each committed update",
                buckets=_NORM_BUCKETS)
            self._h_divergence = registry.histogram(
                "train_center_divergence",
                help="elastic-family ||local - center||_2 per exchange",
                buckets=_NORM_BUCKETS)
            self._c_commits = registry.counter(
                "train_commits_observed_total",
                help="commits the health layer observed")
            self._c_dups = registry.counter(
                "train_duplicate_commits_observed_total",
                help="deduped retried commits observed")
            self._c_rebases = registry.counter(
                "train_rebases_total",
                help="overlapped-exchange rebases applied by workers")
            self._c_pulls = registry.counter(
                "train_worker_pulls_total",
                help="worker bootstrap/center pulls")
            self._c_mass = registry.counter(
                "train_update_mass_total",
                help="summed L2 mass of updates as committed")
            self._c_applied = registry.counter(
                "train_applied_update_mass_total",
                help="summed L2 mass after protocol damping "
                     "(staleness / 1-over-N)")
            self._g_goodput = registry.gauge(
                "train_goodput_ratio",
                help="applied / committed update mass (1.0 = nothing "
                     "damped away)")
            self._g_divergence = registry.gauge(
                "train_center_divergence_last",
                help="most recent ||local - center||_2")

    # -- identity -----------------------------------------------------------
    @staticmethod
    def worker_of(payload: dict):
        """Worker identity of a commit payload: the stamped ``worker``
        index when present, else parsed from the ``commit_id`` the
        stamping client mints (``w<idx>:<counter>``), else None."""
        w = payload.get("worker")
        if w is not None:
            return w
        cid = payload.get("commit_id")
        if isinstance(cid, str) and cid.startswith("w"):
            head = cid.split(":", 1)[0][1:]
            if head.isdigit():
                return int(head)
        return None

    def _worker(self, worker) -> _WorkerStats:
        key = "?" if worker is None else worker
        st = self._workers.get(key)
        if st is None:
            st = self._workers[key] = _WorkerStats(self._window)
        return st

    # -- PS-side observation ------------------------------------------------
    def observe_commit(self, protocol, center, num_updates: int,
                       payload: dict, num_workers: int) -> None:
        """Record one commit, called by the PS loop BEFORE the protocol
        applies it (``center``/``num_updates`` are the pre-commit state
        the staleness and divergence definitions need). Swallows every
        exception — a telemetry bug must not wedge the PS."""
        try:
            stats = protocol.commit_stats(
                center, num_updates, payload, num_workers)
            self.record_commit(worker=self.worker_of(payload), **stats)
        except Exception:
            self._errors += 1
            if self._errors == 1:
                logging.getLogger(__name__).exception(
                    "training-health observe_commit failed (suppressed "
                    "from now on)")

    def record_commit(self, worker=None, staleness: int | None = None,
                      damping: float = 1.0,
                      update_norm: float | None = None,
                      divergence: float | None = None) -> None:
        now = time.time()
        with self._lock:
            st = self._worker(worker)
            st.commits += 1
            st.last_commit_t = now
            st.commit_times.append(now)
            if staleness is not None:
                staleness = int(staleness)
                st.last_staleness = staleness
                st.staleness.append(staleness)
                self._staleness.append(staleness)
            if update_norm is not None:
                self._update_norms.append(float(update_norm))
                self._update_mass += float(update_norm)
                self._applied_mass += float(update_norm) * float(damping)
            if divergence is not None:
                st.last_divergence = float(divergence)
                self._divergence = float(divergence)
        if self._c_commits is not None:
            self._c_commits.inc()
            if staleness is not None:
                # Exemplar: the worker whose commit set this bucket's
                # worst sample — a staleness p99 spike names its source.
                self._h_staleness.observe(
                    staleness, exemplar=f"worker:{worker}")
            if update_norm is not None:
                self._h_norm.observe(float(update_norm))
                self._c_mass.inc(float(update_norm))
                self._c_applied.inc(float(update_norm) * float(damping))
                mass = self._c_mass.value
                if mass > 0:
                    self._g_goodput.set(self._c_applied.value / mass)
            if divergence is not None:
                self._h_divergence.observe(float(divergence),
                                           exemplar=f"worker:{worker}")
                self._g_divergence.set(float(divergence))

    def record_duplicate(self, payload: dict) -> None:
        with self._lock:
            self._worker(self.worker_of(payload)).duplicates += 1
        if self._c_dups is not None:
            self._c_dups.inc()

    # -- worker-side observation --------------------------------------------
    def record_pull(self, worker) -> None:
        with self._lock:
            self._worker(worker).pulls += 1
        if self._c_pulls is not None:
            self._c_pulls.inc()

    def record_rebase(self, worker) -> None:
        with self._lock:
            self._worker(worker).rebases += 1
        if self._c_rebases is not None:
            self._c_rebases.inc()

    def record_window(self, worker, steps: int = 1) -> None:
        """One completed local window of ``steps`` optimizer steps —
        the worker-side work counter statusz pairs against commits (a
        worker stepping but not committing is wedged in the exchange,
        not the compute)."""
        with self._lock:
            st = self._worker(worker)
            st.windows += 1
            st.steps += int(steps)

    # -- context ------------------------------------------------------------
    def attach_ps(self, service) -> None:
        """Attach the live PS service so statusz can fold in its
        ``health()`` rollup (queue depth, update counter, liveness)."""
        self._ps = service

    def set_params_bytes(self, n: int) -> None:
        self._params_bytes = int(n)

    # -- rollups ------------------------------------------------------------
    @property
    def divergence(self) -> float | None:
        return self._divergence

    @property
    def goodput_ratio(self) -> float | None:
        with self._lock:
            if self._update_mass <= 0:
                return None
            return self._applied_mass / self._update_mass

    def staleness_percentiles(self, qs=(50, 90, 99)) -> dict:
        with self._lock:
            xs = list(self._staleness)
        if not xs:
            return {}
        out = {f"p{q}": percentile(xs, q) for q in qs}
        out["max"] = float(max(xs))
        out["samples"] = len(xs)
        return out

    def statusz(self) -> dict:
        """JSON-able snapshot: global staleness/divergence/goodput, the
        per-worker vitals table, the PS rollup, and a per-device memory
        table (typed ``available`` flag — "no data" is not "0 bytes")."""
        now = time.time()
        with self._lock:
            workers = []
            for key in sorted(self._workers, key=str):
                st = self._workers[key]
                row = {
                    "worker": key,
                    "commits": st.commits,
                    "duplicates": st.duplicates,
                    "pulls": st.pulls,
                    "rebases": st.rebases,
                    "windows": st.windows,
                    "steps": st.steps,
                    "last_commit_age_s": (
                        round(now - st.last_commit_t, 3)
                        if st.last_commit_t is not None else None),
                    "last_staleness": st.last_staleness,
                }
                if st.staleness:
                    xs = list(st.staleness)
                    row["staleness_p50"] = round(percentile(xs, 50), 2)
                    row["staleness_p99"] = round(percentile(xs, 99), 2)
                if st.last_divergence is not None:
                    row["divergence"] = round(st.last_divergence, 6)
                if len(st.commit_times) >= 2:
                    span_s = st.commit_times[-1] - st.commit_times[0]
                    if span_s > 0:
                        row["commit_rate_per_s"] = round(
                            (len(st.commit_times) - 1) / span_s, 3)
                workers.append(row)
            out = {
                "t": now,
                "protocol": self.protocol,
                "num_workers": self.num_workers,
                "uptime_s": round(now - self._t0, 3),
                "workers": workers,
                "observe_errors": self._errors,
            }
            if self._update_mass > 0:
                out["goodput"] = {
                    "update_mass": round(self._update_mass, 6),
                    "applied_mass": round(self._applied_mass, 6),
                    "ratio": round(
                        self._applied_mass / self._update_mass, 6),
                }
            if self._divergence is not None:
                out["divergence"] = round(self._divergence, 6)
        stale = self.staleness_percentiles()
        if stale:
            out["staleness"] = {
                k: (round(v, 2) if isinstance(v, float) else v)
                for k, v in stale.items()}
        if self._ps is not None:
            try:
                out["ps"] = self._ps.health()
            except Exception:
                out["ps"] = {"unreachable": True}
        out["memory"] = self.refresh_memory()
        return out

    def refresh_memory(self) -> list[dict]:
        """Probe device memory (typed sentinel, never raises), publish
        the gauges when a registry is attached, and return the per-
        device dict rows statusz renders."""
        try:
            from distkeras_tpu.telemetry.device import (
                all_device_memory,
                publish_memory_gauges,
            )

            if self.registry is not None:
                mems = publish_memory_gauges(
                    self.registry, params_bytes=self._params_bytes)
            else:
                mems = all_device_memory()
            return [m.to_dict() for m in mems]
        except Exception:
            return []
