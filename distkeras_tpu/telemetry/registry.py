"""Counter/gauge/histogram metrics registry.

The single sink the whole system publishes into — serving
(:class:`~distkeras_tpu.serving.metrics.ServingMetrics`, the scheduler),
trainers, the PS/HA layer, and the recompile auditor — replacing the
ad-hoc per-module lists each of those grew separately. One registry is a
point-in-time queryable surface: :func:`~distkeras_tpu.telemetry.
exposition.prometheus_text` renders it as a Prometheus scrape page,
``snapshot()`` as a JSON object for the serving server's ``metricsz``
control verb.

Conventions (Prometheus-shaped, dependency-free):

- metric names ``[a-zA-Z_:][a-zA-Z0-9_:]*``; counters end in ``_total``,
  durations are ``_seconds``;
- labels are a frozen kwargs dict at get-or-create time; the same
  (name, labels) pair always returns the same metric object;
- histograms use fixed cumulative buckets (defaults tuned for
  sub-second latencies) with linear-interpolated percentile estimation.

Percentile semantics are defined ONCE here — :func:`percentile` (exact,
over any sized sequence) and :meth:`Histogram.percentile` (bucket
estimate) agree on the edge cases: empty input raises ``ValueError``,
a single sample is returned exactly for every q.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
import time
from typing import Iterable

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "percentile",
    "sanitize_metric_name",
    "DEFAULT_BUCKETS",
    "log_buckets",
    "hist_state_delta",
    "hist_state_percentile",
    "merge_hist_states",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def sanitize_metric_name(key: str) -> str:
    """Coerce an arbitrary metric key (a stream/history dict key) into a
    valid registry metric name — the ONE encoding of the naming rule
    ``_NAME_RE`` enforces. Invalid characters become ``_``; a leading
    digit gets a ``_`` prefix."""
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in str(key))
    if not out or out[0].isdigit():
        out = "_" + out
    return out

# Cumulative upper bounds tuned for latencies from sub-millisecond decode
# ticks to multi-second cold compiles; +Inf is implicit.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> tuple:
    """A fixed log-spaced bucket layout covering ``[lo, hi]`` with
    ``per_decade`` bounds per factor of 10. Fleet-mergeable histograms
    want every publisher on the SAME layout — building the layout from
    (lo, hi, per_decade) instead of hand-typed tuples makes "same
    layout" a constructor argument, not a copy-paste discipline. Bounds
    are rounded to 6 significant digits so independently constructed
    layouts compare equal."""
    if not (lo > 0 and hi > lo):
        raise ValueError(f"need 0 < lo < hi, got ({lo}, {hi})")
    if per_decade < 1:
        raise ValueError(f"per_decade must be >= 1, got {per_decade}")
    step = 10.0 ** (1.0 / per_decade)
    out, v = [], float(lo)
    while v < hi * (1.0 + 1e-9):
        out.append(float(f"{v:.6g}"))
        v *= step
    return tuple(out)


def hist_state_delta(cur: dict, prev: dict | None) -> dict:
    """Bucket-wise difference of two histogram ``state()`` snapshots of
    the SAME metric (``cur`` observed after ``prev``): the compact
    "what happened since the last push" payload a replica ships to the
    router. ``prev=None`` means the full state IS the delta (first
    push). The delta's min/max are the current snapshot's — bounded by
    one bucket width of the true window extremes, which is exactly the
    accuracy the bucket counts themselves carry. Exemplars ride the
    current per-bucket worst (merge takes per-bucket max, so replaying
    them is idempotent)."""
    if prev is None:
        return dict(cur)
    if list(cur["buckets"]) != list(prev["buckets"]):
        raise ValueError("histogram delta across different bucket layouts")
    counts = [int(c) - int(p)
              for c, p in zip(cur["counts"], prev["counts"])]
    if any(c < 0 for c in counts):
        # The source histogram was reset (replica restart): the full
        # current state is the honest delta.
        return dict(cur)
    out = {
        "buckets": list(cur["buckets"]),
        "counts": counts,
        "count": int(cur["count"]) - int(prev["count"]),
        "sum": float(cur["sum"]) - float(prev["sum"]),
        "min": cur["min"],
        "max": cur["max"],
    }
    if cur.get("exemplars"):
        out["exemplars"] = cur["exemplars"]
    return out


def merge_hist_states(*states: dict) -> dict:
    """Exact bucket-wise merge of histogram ``state()`` dicts sharing
    one layout — associative and commutative by construction (integer
    adds + min/max), so fleet aggregation can fold per-replica deltas
    in any arrival order and any grouping. Returns a new state dict."""
    states = [s for s in states if s]
    if not states:
        raise ValueError("merge of zero histogram states")
    base = states[0]
    counts = [0] * len(base["counts"])
    total, sm = 0, 0.0
    mn, mx = math.inf, -math.inf
    exemplars: list = [None] * len(counts)
    for s in states:
        if list(s["buckets"]) != list(base["buckets"]):
            raise ValueError(
                "histogram merge across different bucket layouts")
        for i, c in enumerate(s["counts"]):
            counts[i] += int(c)
        total += int(s["count"])
        sm += float(s["sum"])
        if s["count"]:
            mn = min(mn, float(s["min"]))
            mx = max(mx, float(s["max"]))
        for i, ex in enumerate(s.get("exemplars") or []):
            if ex is None:
                continue
            cur = exemplars[i]
            if cur is None or float(ex[0]) > float(cur[0]):
                exemplars[i] = [float(ex[0]), ex[1]]
    out = {
        "buckets": list(base["buckets"]),
        "counts": counts,
        "count": total,
        "sum": sm,
        "min": (mn if total else None),
        "max": (mx if total else None),
    }
    if any(e is not None for e in exemplars):
        out["exemplars"] = exemplars
    return out


def hist_state_percentile(state: dict, q: float) -> float:
    """Bucket-interpolated percentile over a histogram ``state()`` dict
    — the ONE estimator live histograms, fleet merges, and timeseries
    windows all share, so a fleet p99 and a single-replica p99 disagree
    only by what their bucket counts disagree by. Edge cases match
    :func:`percentile`: empty raises, a single sample is returned
    exactly (the sum of one sample IS the sample)."""
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"q must be in [0, 100], got {q}")
    n = int(state["count"])
    if n == 0:
        raise ValueError("percentile of empty histogram")
    if n == 1:
        return float(state["sum"])
    counts = state["counts"]
    bounds = state["buckets"]
    lo_obs = float(state["min"]) if state.get("min") is not None else 0.0
    hi_obs = (float(state["max"]) if state.get("max") is not None
              else float(bounds[-1]))
    rank = (q / 100.0) * n
    acc = 0.0
    for i, c in enumerate(counts):
        if acc + c >= rank and c > 0:
            lo = bounds[i - 1] if i > 0 else lo_obs
            hi = bounds[i] if i < len(bounds) else hi_obs
            frac = (rank - acc) / c
            est = lo + (hi - lo) * frac
            return min(max(est, lo_obs), hi_obs)
        acc += c
    return hi_obs


def percentile(values: Iterable[float], q: float) -> float:
    """Linear-interpolated percentile of ``values`` (any sized iterable);
    ``q`` in [0, 100]. Raises ``ValueError`` on empty input; a single
    sample is returned exactly for every q. The ONE percentile definition
    serving metrics, step timers, and histograms all share."""
    xs = sorted(float(v) for v in values)
    if not xs:
        raise ValueError("percentile of empty sequence")
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"q must be in [0, 100], got {q}")
    if len(xs) == 1:
        return xs[0]
    pos = (q / 100.0) * (len(xs) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] * (1.0 - frac) + xs[hi] * frac


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonic float counter (``inc`` only)."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Metric):
    """Set/inc/dec point-in-time value."""

    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram with percentile estimation.

    ``observe(v)`` is O(log buckets); memory is O(buckets) regardless of
    sample count — the unbounded-list failure mode of per-module metric
    lists cannot recur here. ``percentile(q)`` linearly interpolates
    within the bucket containing the q-th sample, clamped to the observed
    [min, max] so estimates never leave the data's range.
    """

    kind = "histogram"

    def __init__(self, name, help="", labels=None, buckets=None):
        super().__init__(name, help, labels)
        bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.bucket_bounds = bs  # +Inf bucket is implicit (the overflow)
        self._counts = [0] * (len(bs) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        # Per-bucket exemplar: (worst value, label) — the label is a
        # trace_id in serving use, so a p99 spike on the scrape page
        # links straight to that request's flight-recorder timeline.
        # Fixed-size (one slot per bucket) and updated only when a new
        # within-bucket maximum lands, so steady-state cost is a compare.
        self._exemplars: list[tuple[float, object] | None] = (
            [None] * (len(bs) + 1))

    def observe(self, v: float, exemplar=None) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bucket_bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if exemplar is not None:
                cur = self._exemplars[i]
                if cur is None or v > cur[0]:
                    self._exemplars[i] = (v, exemplar)

    def exemplars(self) -> dict[str, dict]:
        """Worst-sample exemplar per occupied bucket, keyed by the
        bucket's ``le`` upper bound (``"+Inf"`` for the overflow)."""
        with self._lock:
            pairs = list(self._exemplars)
        out = {}
        for i, pair in enumerate(pairs):
            if pair is None:
                continue
            bound = (self.bucket_bounds[i]
                     if i < len(self.bucket_bounds) else math.inf)
            key = "+Inf" if bound == math.inf else repr(bound)
            out[key] = {"value": pair[0], "trace_id": pair[1]}
        return out

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float | None:
        return self._sum / self._count if self._count else None

    def cumulative_counts(self) -> list[tuple[float, int]]:
        """(upper_bound, cumulative_count) pairs, ending at (+inf, count)
        — the Prometheus ``_bucket{le=...}`` series."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for bound, c in zip(self.bucket_bounds, counts):
            acc += c
            out.append((bound, acc))
        out.append((math.inf, acc + counts[-1]))
        return out

    def percentile(self, q: float) -> float:
        """Bucket-interpolated percentile estimate; agrees with the exact
        :func:`percentile` on the edge cases (empty raises, one sample is
        returned exactly)."""
        return hist_state_percentile(self.state(exemplars=False), q)

    # -- mergeable-histogram surface ----------------------------------------
    def state(self, exemplars: bool = True) -> dict:
        """JSON-able full snapshot of the histogram's mergeable state:
        per-bucket counts (NON-cumulative), count/sum/min/max, bucket
        layout, and (optionally) the per-bucket worst-sample exemplars.
        ``state()`` dicts are the unit of fleet telemetry: deltas
        (:func:`hist_state_delta`) ship over the wire, merges
        (:func:`merge_hist_states` / :meth:`merge_state`) fold them."""
        with self._lock:
            out = {
                "buckets": list(self.bucket_bounds),
                "counts": list(self._counts),
                "count": self._count,
                "sum": self._sum,
                "min": (self._min if self._count else None),
                "max": (self._max if self._count else None),
            }
            if exemplars and any(e is not None for e in self._exemplars):
                out["exemplars"] = [
                    None if e is None else [e[0], e[1]]
                    for e in self._exemplars]
        return out

    def merge_state(self, state: dict) -> None:
        """Fold a ``state()``/delta dict into this histogram — the
        bucket-exact merge the router applies to every pushed replica
        delta. Requires an identical bucket layout (fleet mergeability
        is why layouts are fixed at construction). Commutative and
        associative over the bucket state: any fold order yields the
        same counts/sum/min/max."""
        if list(state["buckets"]) != list(self.bucket_bounds):
            raise ValueError(
                f"cannot merge {self.name!r}: bucket layout "
                f"{state['buckets']} != {list(self.bucket_bounds)}")
        exemplars = state.get("exemplars") or []
        with self._lock:
            for i, c in enumerate(state["counts"]):
                self._counts[i] += int(c)
            self._count += int(state["count"])
            self._sum += float(state["sum"])
            if state["count"]:
                if state["min"] is not None:
                    self._min = min(self._min, float(state["min"]))
                if state["max"] is not None:
                    self._max = max(self._max, float(state["max"]))
            for i, ex in enumerate(exemplars):
                if ex is None or i >= len(self._exemplars):
                    continue
                cur = self._exemplars[i]
                if cur is None or float(ex[0]) > cur[0]:
                    self._exemplars[i] = (float(ex[0]), ex[1])

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram of the same bucket layout into this
        one (see :meth:`merge_state`)."""
        self.merge_state(other.state())


class MetricsRegistry:
    """Get-or-create home for metrics, keyed by (name, labels).

    Asking twice for the same (name, labels) returns the same object;
    asking with a different metric kind for an existing name raises —
    publisher modules can therefore declare their metrics at call sites
    without coordinating ownership.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, _Metric] = {}
        self._created = time.time()

    def _get_or_create(self, cls, name, help, labels, **kw):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as {m.kind}"
                    )
                return m
            m = cls(name, help=help, labels=labels, **kw)
            self._metrics[key] = m
            return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", buckets=None,
                  **labels) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def remove(self, metric) -> None:
        """Unregister a metric instance (e.g. a superseded info-style
        labeled series that would otherwise live in every scrape
        forever). No-op when it was never (or already un-) registered;
        existing handles to the object keep working but stop being
        collected."""
        with self._lock:
            for key, m in list(self._metrics.items()):
                if m is metric:
                    del self._metrics[key]
                    return

    def collect(self) -> list[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> dict:
        """JSON-able point-in-time dump (the ``metricsz`` JSON body)."""
        out: dict = {}
        for m in self.collect():
            key = m.name
            if m.labels:
                key += "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(m.labels.items())) + "}"
            if m.kind == "histogram":
                entry: dict = {"kind": m.kind, "count": m.count,
                               "sum": round(m.sum, 9)}
                if m.count:
                    entry.update({
                        "p50": m.percentile(50), "p90": m.percentile(90),
                        "p99": m.percentile(99), "mean": m.mean,
                    })
                    ex = m.exemplars()
                    if ex:
                        entry["exemplars"] = ex
                out[key] = entry
            else:
                out[key] = {"kind": m.kind, "value": m.value}
        return out
