"""Recompile auditor: make "compiled once" a runtime invariant.

The performance model of every hot path in this repo rests on compile
counts: the serving engine promises ONE decode executable for the
server's lifetime, prefill is bounded at one program per power-of-two
bucket, trainers compile one step per distinct batch geometry. A silent
retrace (a drifted dtype, a weak-type promotion, a shape that slipped
through bucketing) turns a microseconds dispatch into a seconds-long
compile — "it got slower and nobody noticed" until tail latency pages
someone.

Until this module, the only guard was a benchmark assertion
(``benchmarks/serving_bench.py`` asserting ``decode_compile_count() == 1``).
:class:`RecompileAuditor` moves the check into the runtime:

- :meth:`RecompileAuditor.wrap` wraps a jitted callable; each compile is
  detected (via the jit cache-size probe when available, else by tracking
  distinct abstract input signatures) and recorded with the triggering
  abstract shapes;
- :meth:`RecompileAuditor.arm` — after warmup — turns any FURTHER compile
  of the named (or all) wrapped callables into a loud
  :class:`RecompileError` at the exact call that triggered it, with the
  offending signature in the message.

Detection cost per call is one ``_cache_size()`` probe (an int read);
signatures are only materialized when a compile actually happened, so an
armed auditor is cheap enough to leave on in production serving.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable

__all__ = [
    "RecompileAuditor",
    "RecompileError",
    "CompileEvent",
    "abstract_signature",
]


class RecompileError(RuntimeError):
    """An armed callable compiled again after warmup."""


def _leaf_sig(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        try:
            return f"{dtype}[{','.join(str(d) for d in shape)}]"
        except Exception:
            return f"{type(x).__name__}"
    # Non-array leaves retrace on VALUE (they are static or hashed into
    # weak-typed constants) — include the value, not just the type.
    return f"{type(x).__name__}={x!r}"


def abstract_signature(args: tuple, kwargs: dict) -> str:
    """Compact dtype[shape] signature of a call's abstract values — the
    identity jit traces on (up to weak types / static args)."""
    import jax

    leaves, treedef = jax.tree.flatten((args, kwargs))
    try:
        parts = [_leaf_sig(leaf) for leaf in leaves]
    except Exception:  # e.g. donated buffers in exotic backends
        return "<unavailable>"
    return f"({', '.join(parts)}) tree={treedef}"


@dataclasses.dataclass
class CompileEvent:
    """One observed compile: which callable, which call, what shapes."""

    name: str
    call_index: int
    signature: str
    armed: bool


class _AuditedFn:
    """Callable wrapper counting compiles of one jitted function.

    Transparent: ``__getattr__`` delegates to the wrapped callable, so
    probes like ``_cache_size`` (used by ``decode_compile_count``) and
    ``lower``/``compile`` still work through the wrapper.
    """

    def __init__(self, fn: Callable, name: str, auditor: "RecompileAuditor"):
        self._fn = fn
        self.name = name
        self._auditor = auditor
        self._lock = threading.Lock()
        self.calls = 0
        self.compiles = 0
        self.armed = False
        probe = getattr(fn, "_cache_size", None)
        self._probe = probe if callable(probe) else None
        self._seen_sigs: set[str] = set()
        self._max_size = self._cache_size() or 0

    def _cache_size(self) -> int | None:
        if self._probe is None:
            return None
        try:
            return int(self._probe())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        with self._lock:
            self.calls += 1
        size = self._cache_size()
        if size is None:
            # No probe (older/newer jax, or a plain callable): fall back to
            # signature-set tracking — a new abstract signature IS a trace.
            sig = abstract_signature(args, kwargs)
            with self._lock:
                fresh = sig not in self._seen_sigs
                self._seen_sigs.add(sig)
            out = self._fn(*args, **kwargs)
            if fresh:
                self._auditor._on_compile(self, sig)
            return out
        out = self._fn(*args, **kwargs)
        after = self._cache_size()
        grew = 0
        with self._lock:
            # Max-size tracking (not before/after around THIS call): with
            # concurrent callers (async trainer worker threads share one
            # window step) each cache-size increment is attributed exactly
            # once, by whichever caller observes it first.
            if after is not None and after > self._max_size:
                grew = after - self._max_size
                self._max_size = after
        if grew:
            # Signature materialized only on the (rare) compile; shape and
            # dtype are aval metadata, readable even off donated buffers.
            self._auditor._on_compile(
                self, abstract_signature(args, kwargs), n=grew)
        return out

    def __getattr__(self, name: str) -> Any:
        return getattr(self._fn, name)


class RecompileAuditor:
    """Audits a set of wrapped jitted callables.

    ``registry``: optional :class:`~distkeras_tpu.telemetry.registry.
    MetricsRegistry`; every observed compile increments
    ``recompile_auditor_compiles_total{fn=...}`` so the scrape endpoint
    shows compile counts live.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._fns: dict[str, _AuditedFn] = {}
        self.events: list[CompileEvent] = []
        self._registry = registry

    def wrap(self, fn: Callable, name: str) -> _AuditedFn:
        """Wrap ``fn`` (typically a ``jax.jit`` product) under ``name``;
        returns the transparent audited callable to use in its place."""
        wrapped = _AuditedFn(fn, name, self)
        with self._lock:
            if name in self._fns:
                raise ValueError(f"auditor already wraps a fn named {name!r}")
            self._fns[name] = wrapped
        return wrapped

    def _on_compile(self, fn: _AuditedFn, signature: str, n: int = 1) -> None:
        with fn._lock:  # compiles/calls share the wrapper's lock
            fn.compiles += n
            ev = CompileEvent(fn.name, fn.calls, signature, fn.armed)
        with self._lock:
            self.events.append(ev)
        if self._registry is not None:
            self._registry.counter(
                "recompile_auditor_compiles_total",
                help="compiles observed by the recompile auditor",
                fn=fn.name,
            ).inc(n)
        if fn.armed:
            raise RecompileError(
                f"{fn.name!r} recompiled after warmup (compile "
                f"#{fn.compiles}, call #{fn.calls}) — triggering abstract "
                f"signature: {signature}"
            )

    def arm(self, *names: str) -> None:
        """Fail loudly on any further compile of the named callables (all
        wrapped callables when no names given). Call after warmup — e.g.
        after the first decode iteration, or after the first train step."""
        with self._lock:
            targets = names or tuple(self._fns)
            for n in targets:
                if n not in self._fns:
                    raise KeyError(f"auditor wraps no fn named {n!r}")
                self._fns[n].armed = True

    def disarm(self, *names: str) -> None:
        with self._lock:
            for n in (names or tuple(self._fns)):
                self._fns[n].armed = False

    def compiles(self, name: str) -> int:
        return self._fns[name].compiles

    def total_compiles(self) -> int:
        return sum(f.compiles for f in self._fns.values())

    def report(self) -> dict:
        """Per-callable compile/call counts with triggering signatures —
        JSON-able, printed by ``run.py --audit-recompiles`` at exit."""
        with self._lock:
            events = list(self.events)
            fns = dict(self._fns)
        out = {}
        for name, fn in fns.items():
            out[name] = {
                "calls": fn.calls,
                "compiles": fn.compiles,
                "armed": fn.armed,
                "signatures": [e.signature for e in events if e.name == name],
            }
        return out
