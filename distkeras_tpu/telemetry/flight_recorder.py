"""Flight recorder: a bounded black box of recent serving activity.

A crashed replica's aggregate metrics die with it; its last seconds of
STATE — which requests were in flight, what the engine was doing, which
request blew its SLO — are exactly what the post-mortem needs. The
recorder keeps three fixed-size rings per process:

- **events** — engine/process state transitions (admit, shutdown, param
  swap, failure) as ``(wall_ts, kind, fields)`` tuples;
- **timelines** — the most recent finished per-request
  :class:`~distkeras_tpu.telemetry.request_trace.TimelineRecord` dicts;
- **slow exemplars** — full timelines of requests that exceeded the
  latency SLO, kept in their own ring so a burst of ordinary traffic
  cannot wash the interesting ones out of the window.

Memory stance: every ring is a **preallocated fixed-length list with a
cursor** — recording overwrites the oldest entry in place and never
grows a container, so a recorder armed on a multi-day serving process
costs the same bytes on day 30 as at boot (the span tracer's
``max_events`` concern, solved by overwrite instead of drop: for a black
box the RECENT past is the valuable part).

Dumps: :meth:`dump` writes one JSON file (tmp + rename, so a reader
never sees a torn file); :meth:`crash_dump` is the best-effort
exception-path variant the engine calls when its loop dies — the
"last words" file the cluster supervisor collects off a dead replica and
references in its restart log. A SIGKILL'd process (the chaos test's
hard kill of a child REPLICA PROCESS) cannot write last words — that
limitation is fundamental; in-process crash paths (engine task failure
or cancellation, SIGTERM drain) all dump.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["FlightRecorder", "load_flight_dump"]


class _Ring:
    """Fixed-size overwrite ring: preallocated slots + cursor."""

    __slots__ = ("_slots", "_cursor", "count")

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self._slots: list = [None] * int(capacity)
        self._cursor = 0
        self.count = 0  # total ever recorded (monotonic)

    def put(self, item) -> None:
        self._slots[self._cursor] = item
        self._cursor = (self._cursor + 1) % len(self._slots)
        self.count += 1

    def items(self) -> list:
        """Oldest-to-newest live entries."""
        n = len(self._slots)
        if self.count < n:
            return [s for s in self._slots[:self.count]]
        return (self._slots[self._cursor:] + self._slots[:self._cursor])


class FlightRecorder:
    """Bounded ring buffers of recent events + request timelines.

    ``capacity``: state-transition event ring size.
    ``timeline_capacity``: finished-request timeline ring size.
    ``slow_capacity``: SLO-violation exemplar ring size.
    ``dump_path``: where :meth:`dump`/:meth:`crash_dump` write when called
    with no explicit path — the replica's "last words" location the
    supervisor knows to look at.
    ``source``: process identity stamped into dumps (replica id, pid).
    ``wide_events``: optional
    :class:`~distkeras_tpu.telemetry.wide_events.WideEventStore` whose
    ring TAIL rides along in every dump — the flat per-request facts of
    the last requests served before death, available even when no
    timeline store was armed (the engine attaches its store here).
    """

    def __init__(self, capacity: int = 256, *, timeline_capacity: int = 128,
                 slow_capacity: int = 32, dump_path: str | None = None,
                 source: str = "", wide_events=None,
                 wide_tail: int = 64):
        self._lock = threading.Lock()
        self._events = _Ring(capacity)
        self._timelines = _Ring(timeline_capacity)
        self._slow = _Ring(slow_capacity)
        self.dump_path = dump_path
        self.source = source or f"pid:{os.getpid()}"
        self.dumps_written = 0
        self.wide_events = wide_events
        self.wide_tail = int(wide_tail)

    # -- recording -----------------------------------------------------------
    def record_event(self, kind: str, **fields) -> None:
        """One state transition. Guard call sites with ``if recorder is
        not None`` — with no recorder the serving hot path must not even
        build the kwargs."""
        with self._lock:
            self._events.put((time.time(), kind, fields or None))

    def record_timeline(self, record: dict, slow: bool = False) -> None:
        """A finished request's timeline dict; ``slow=True`` (the caller's
        SLO verdict) ALSO pins it in the exemplar ring."""
        with self._lock:
            self._timelines.put(record)
            if slow:
                self._slow.put(record)

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "source": self.source,
                "events_recorded": self._events.count,
                "events_capacity": len(self._events._slots),
                "timelines_recorded": self._timelines.count,
                "timelines_capacity": len(self._timelines._slots),
                "slow_exemplars": self._slow.count,
                "dump_path": self.dump_path,
                "dumps_written": self.dumps_written,
            }

    def slow_exemplars(self) -> list[dict]:
        with self._lock:
            return list(self._slow.items())

    def dump_dict(self) -> dict:
        with self._lock:
            out = {
                "source": self.source,
                "dumped_at": time.time(),
                "events": [
                    {"ts": ts, "kind": kind,
                     **({"fields": fields} if fields else {})}
                    for ts, kind, fields in self._events.items()
                ],
                "timelines": list(self._timelines.items()),
                "slow_exemplars": list(self._slow.items()),
                "events_recorded": self._events.count,
                "timelines_recorded": self._timelines.count,
            }
        # Outside the recorder lock: the store has its own (and a
        # wedged store must not deadlock a crash dump against an
        # appending engine thread).
        if self.wide_events is not None:
            try:
                out["wide_events_tail"] = self.wide_events.tail(
                    self.wide_tail)
                out["wide_events_stats"] = self.wide_events.stats()
            except Exception:
                # Last-words writes are best-effort end to end.
                pass
        return out

    # -- dumping -------------------------------------------------------------
    def dump(self, path: str | None = None) -> str:
        """Write the black box as one JSON file (atomic tmp + rename);
        returns the path written."""
        path = path or self.dump_path
        if not path:
            raise ValueError("no dump path configured")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(self.dump_dict(), f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.dumps_written += 1
        return path

    def crash_dump(self, error: str | None = None) -> str | None:
        """Best-effort last-words write on the failure path: records the
        terminal event, dumps to ``dump_path``, and SWALLOWS any write
        failure — a broken disk must not mask the original exception the
        engine is about to re-raise. None when no path is configured or
        the write failed."""
        if error is not None:
            self.record_event("crash", error=error)
        if not self.dump_path:
            return None
        try:
            return self.dump()
        except Exception:
            return None


def load_flight_dump(path: str) -> dict:
    """Read a dump file back (supervisor last-words collection, tests)."""
    with open(path) as f:
        return json.load(f)
