"""Device-side observability: memory accounting and XLA profiling.

Two things only the accelerator runtime can answer — "how much HBM is
actually in use, and how close to the limit are we" and "what did XLA
run on the chip" — promoted here from their previous scattered homes
(``tracing.trace`` for the profiler, an inline ``memory_stats()`` probe
inside the trainers' device-cache heuristic for the accounting).

**Memory accounting.** ``device.memory_stats()`` is an optional backend
API: TPU/GPU runtimes publish it, the CPU backend may not, and some
backends raise instead of returning. Every probe in the repo therefore
goes through :func:`device_memory`, which never raises and returns a
typed :class:`DeviceMemory` whose ``available`` flag distinguishes
"the backend has no data" from "0 bytes in use" — statusz/metricsz
render the former as ``unavailable`` instead of a lying zero.
:func:`publish_memory_gauges` pushes the same probe into a
:class:`~distkeras_tpu.telemetry.registry.MetricsRegistry` as per-device
labeled gauges (``device_bytes_in_use`` / ``device_bytes_limit`` /
``device_memory_headroom_bytes``), alongside the workload-side bytes the
caller already knows (params, KV pool) so one scrape shows both sides of
the headroom equation.

**Profiling.** :func:`profile_trace` is the ``jax.profiler``
start/stop pair as a context manager — the XLA-timeline complement to
the host-side spans in :mod:`.spans`. ``run.py`` wires it as
``--profile-out`` on both train and serve; ``tracing.trace`` remains as
a deprecated shim forwarding here.
"""

from __future__ import annotations

import contextlib
import dataclasses

__all__ = [
    "DeviceMemory",
    "device_memory",
    "all_device_memory",
    "publish_memory_gauges",
    "profile_trace",
]


@dataclasses.dataclass(frozen=True)
class DeviceMemory:
    """One device's memory picture at probe time.

    ``available=False`` is the typed "no data" sentinel: the backend has
    no ``memory_stats()`` (or it raised), and every byte field is None —
    deliberately NOT 0, so a dashboard can never mistake a blind backend
    for an empty one.
    """

    device: str
    available: bool
    bytes_in_use: int | None = None
    bytes_limit: int | None = None
    peak_bytes_in_use: int | None = None

    @property
    def headroom_bytes(self) -> int | None:
        """``bytes_limit - bytes_in_use`` when both are known."""
        if self.bytes_in_use is None or self.bytes_limit is None:
            return None
        return self.bytes_limit - self.bytes_in_use

    def to_dict(self) -> dict:
        out = {"device": self.device, "available": self.available,
               "bytes_in_use": self.bytes_in_use,
               "bytes_limit": self.bytes_limit,
               "peak_bytes_in_use": self.peak_bytes_in_use}
        hr = self.headroom_bytes
        if hr is not None:
            out["headroom_bytes"] = hr
        return out


def _device_name(device) -> str:
    did = getattr(device, "id", None)
    if did is not None:
        return f"{getattr(device, 'platform', 'dev')}:{did}"
    return str(device)


def device_memory(device) -> DeviceMemory:
    """Probe one device's ``memory_stats()``; NEVER raises. Backends
    without the API (or whose probe raises, or which return an empty /
    None result) yield the ``available=False`` sentinel."""
    name = _device_name(device)
    stats = None
    try:
        fn = getattr(device, "memory_stats", None)
        if fn is not None:
            stats = fn()
    except Exception:
        stats = None
    if not stats:
        return DeviceMemory(device=name, available=False)

    def _num(key):
        v = stats.get(key)
        return int(v) if isinstance(v, (int, float)) else None

    return DeviceMemory(
        device=name,
        available=True,
        bytes_in_use=_num("bytes_in_use"),
        bytes_limit=_num("bytes_limit"),
        peak_bytes_in_use=_num("peak_bytes_in_use"),
    )


def all_device_memory(devices=None) -> list[DeviceMemory]:
    """Probe every (given or local) device. Importing jax lazily keeps
    this module importable in the stdlib-only tooling environment."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    return [device_memory(d) for d in devices]


def publish_memory_gauges(
    registry,
    devices=None,
    params_bytes: int | None = None,
    kv_pool_bytes: int | None = None,
    kv_pool_peak_bytes: int | None = None,
    params_bytes_by_device: dict | None = None,
    kv_bytes_by_device: dict | None = None,
) -> list[DeviceMemory]:
    """Publish per-device memory gauges (and the caller's workload-side
    byte counts) into ``registry``; returns the probed list so callers
    can also render it (healthz, statusz).

    Per device: ``device_memory_stats_available{device=...}`` is ALWAYS
    set (1/0 — the scrapeable face of the typed sentinel); the byte
    gauges (``device_bytes_in_use`` / ``device_bytes_limit`` /
    ``device_memory_headroom_bytes`` / ``device_peak_bytes_in_use``) are
    set only when the backend reports them, so an unavailable backend
    shows NO byte series rather than a flat 0.

    ``params_bytes_by_device`` / ``kv_bytes_by_device``: device-name →
    resident-bytes maps from a GSPMD-sharded workload (the serving
    engine computes them from its arrays' addressable shards) —
    published as ``model_params_bytes{device=...}`` /
    ``kv_pool_reserved_bytes{device=...}`` labeled series so a sharded
    engine's params/KV footprint is attributable per shard, alongside
    the unlabeled engine-wide totals.
    """
    mems = all_device_memory(devices)
    for mem in mems:
        registry.gauge(
            "device_memory_stats_available",
            help="1 when the backend publishes memory_stats() for this "
                 "device; 0 = no data (byte gauges absent, not zero)",
            device=mem.device).set(1.0 if mem.available else 0.0)
        if not mem.available:
            continue
        pairs = (
            ("device_bytes_in_use", "live device bytes in use",
             mem.bytes_in_use),
            ("device_bytes_limit", "device memory capacity",
             mem.bytes_limit),
            ("device_peak_bytes_in_use", "high-water device bytes",
             mem.peak_bytes_in_use),
            ("device_memory_headroom_bytes",
             "bytes_limit - bytes_in_use", mem.headroom_bytes),
        )
        for name, help_, val in pairs:
            if val is not None:
                registry.gauge(name, help=help_, device=mem.device).set(val)
    if params_bytes is not None:
        registry.gauge(
            "model_params_bytes",
            help="bytes of the live model parameters").set(params_bytes)
    if kv_pool_bytes is not None:
        registry.gauge(
            "kv_pool_reserved_bytes",
            help="bytes reserved by the KV block pool").set(kv_pool_bytes)
    if kv_pool_peak_bytes is not None:
        registry.gauge(
            "kv_pool_peak_bytes",
            help="high-water bytes of KV blocks in use").set(
                kv_pool_peak_bytes)
    for name, help_, by_dev in (
        ("model_params_bytes",
         "bytes of the live model parameters resident on this device "
         "(sharded engines: one series per mesh device)",
         params_bytes_by_device),
        ("kv_pool_reserved_bytes",
         "bytes of KV cache/pool resident on this device (sharded "
         "engines: one series per mesh device)", kv_bytes_by_device),
    ):
        if by_dev:
            for dev, nbytes in sorted(by_dev.items()):
                registry.gauge(name, help=help_, device=dev).set(nbytes)
    return mems


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a ``jax.profiler`` trace of everything inside the block
    (view in TensorBoard/Perfetto) — the XLA-timeline complement to the
    host spans. The ONE copy of the start/stop pairing;
    ``tracing.trace`` forwards here as a deprecated shim."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
