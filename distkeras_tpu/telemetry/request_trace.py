"""Per-request distributed tracing: trace ids, timeline records, stores.

PR 2's spans answer "where does THIS PROCESS spend its wall clock"; they
cannot follow one request across the serving cluster's four processes
(client -> router -> replica server -> engine), where a retry, an
affinity spill, or a slow prefill on one hop is invisible from every
other hop's aggregate histograms. This module adds the request-scoped
layer:

- **trace ids** — :func:`new_trace_id` mints a short opaque id; the
  client generates one per request (or the router mints one for clients
  that don't) and it rides the JSONL protocol end to end, so every hop
  tags its spans, events, and error lines with the same id;
- **timeline records** (:class:`TimelineRecord`) — one per request per
  hop: an ordered event list (submit, admit with queue wait, prefill
  chunks with device time, first token, terminal status) plus summary
  data (cache-hit tokens, decode iterations, retries/replica hops).
  The engine assembles one per served request; the router assembles one
  per routed request with its dispatch/retry events;
- **stores** (:class:`TraceStore`) — bounded per-process map of
  completed records by trace id, queryable over the wire via the
  ``tracez`` control verb; the router's ``tracez`` merges its own record
  with every replica's into ONE cross-process trace
  (:func:`merge_trace`);
- **Chrome export** (:func:`chrome_trace`) — renders records in the same
  ``traceEvents`` JSON the span tracer emits, ONE LANE PER REQUEST
  (``tid`` = request), so Perfetto shows a swimlane per request with its
  queue wait, prefill chunks, and decode phase laid end to end.

Cost stance: everything here is **per-request**, never per-token — a
record is a list of a dozen small events over a request's lifetime.  The
per-token hot path (the decode loop's ``_push_token``) never touches a
timeline; with no store or recorder configured the engine skips record
construction entirely, keeping PR 2's disabled-path bar.

Timestamps are ``time.time()`` (wall clock): cross-process merging needs
one clock every hop shares, and NTP-level skew is fine at the >= 1 ms
granularity request phases live at. Durations inside one process are
measured monotonically by their publishers and attached as ``dur_s``
attrs, so skew never corrupts a span's length.
"""

from __future__ import annotations

import binascii
import json
import os
import threading
import time
from collections import OrderedDict

__all__ = [
    "new_trace_id",
    "sanitize_trace_id",
    "TimelineRecord",
    "TailRetention",
    "TraceStore",
    "merge_trace",
    "chrome_trace",
    "export_chrome_trace",
]


def new_trace_id() -> str:
    """16 hex chars of OS randomness: unique enough for a fleet's
    retention window, short enough to read aloud off a log line."""
    return binascii.hexlify(os.urandom(8)).decode()


def sanitize_trace_id(trace_id) -> str | None:
    """The ONE sanitizer for wire-supplied trace ids (Request ctor, the
    router's minting path, the server's error lines all use it): cap the
    length against junk, and strip ``#`` — :class:`TraceStore` uses
    ``<id>#<n>`` keys for duplicate hops, so a client-chosen id
    containing ``#`` could address ANOTHER request's hop records. None
    for empty/falsy input (callers mint a fresh id)."""
    if not trace_id:
        return None
    tid = str(trace_id).replace("#", "")[:64]
    return tid or None


class TimelineRecord:
    """One request's life on one hop: ordered events plus summary data.

    ``role`` is ``"engine"`` (a replica's serving engine) or ``"router"``
    (the cluster front port); ``source`` identifies the process/replica
    (e.g. ``"r0"``, ``"engine:pid4242"``). Events are
    ``[name, wall_ts, attrs-or-None]`` triples appended in order by the
    single owner (the engine loop or the router handler — no locking
    needed until the record is finalized into a :class:`TraceStore`).
    """

    __slots__ = ("trace_id", "role", "source", "t_start", "events", "data")

    def __init__(self, trace_id: str, role: str, source: str = ""):
        self.trace_id = trace_id
        self.role = role
        self.source = source
        self.t_start = time.time()
        self.events: list[list] = []
        self.data: dict = {}

    def event(self, name: str, **attrs) -> None:
        """Append one event at the current wall clock. ``dur_s`` in attrs
        marks a timed phase (rendered as a Chrome complete event whose
        START is ``ts - dur_s``); other attrs are annotations."""
        self.events.append([name, time.time(), attrs or None])

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "role": self.role,
            "source": self.source,
            "t_start": self.t_start,
            "events": [list(e) for e in self.events],
            "data": dict(self.data),
        }


class TailRetention:
    """Done-time keep/discard scorer for finished timelines.

    A bounded trace ring under hot traffic evicts exactly the records an
    operator wants: the errors, the SLO breaches, the latency tail. This
    scorer decides AT COMPLETION — when a request's whole story is known
    — whether its timeline is worth holding past the sliding window,
    returning a keep reason or ``None`` (bulk discard, dropped early):

    - ``"error"`` — terminal status other than ok: always kept;
    - ``"slo"`` — the engine's SLO verdict said slow: always kept;
    - ``"tail"`` — latency at/above the running ``tail_q`` percentile
      of ITS KIND (per-kind, so a batch scoring job's normal minutes
      don't drown interactive sampling's abnormal seconds), after a
      ``warmup`` of samples for that kind;
    - ``"rare"`` — one of the first ``rare_below`` completions for its
      (tenant, kind) pair: a new tenant's first requests are kept even
      when perfectly healthy, because "what did it look like when it
      started" is exactly what gets asked later;
    - ``"baseline"`` — a deterministic 1-in-``baseline_every`` counter
      sample of healthy traffic (a counter, not an RNG, so tests and
      replays see the same keeps).

    Reasons are priority-ordered (:data:`REASON_PRIORITY`, lower keeps
    longer) for the keeper reservoir's eviction; latency tracking uses
    the same fixed histogram layout the wide-event store queries with,
    so "the tail" here and a ``queryz`` p-tail agree bucket-for-bucket.
    """

    #: Eviction order within a full keeper reservoir: higher numbers
    #: evict first. ``pinned`` is assigned by the store, never here.
    REASON_PRIORITY = {"pinned": 0, "error": 1, "slo": 2, "tail": 3,
                       "rare": 4, "baseline": 5}

    def __init__(self, tail_q: float = 90.0, warmup: int = 20,
                 rare_below: int = 3, baseline_every: int = 32):
        if not 0.0 < tail_q < 100.0:
            raise ValueError(f"tail_q must be in (0, 100), got {tail_q}")
        self.tail_q = float(tail_q)
        self.warmup = max(1, int(warmup))
        self.rare_below = max(0, int(rare_below))
        self.baseline_every = max(1, int(baseline_every))
        self._lock = threading.Lock()
        self._seen = 0
        self._pair_counts: dict[tuple, int] = {}
        self._kind_hists: dict[str, object] = {}

    def _kind_hist(self, kind: str):
        h = self._kind_hists.get(kind)
        if h is None:
            # Deferred import: registry is dependency-free but this
            # module is imported by crash tooling that wants the
            # cheapest possible import graph.
            from distkeras_tpu.telemetry.registry import Histogram
            from distkeras_tpu.telemetry.wide_events import (
                WIDE_HIST_BUCKETS)
            h = Histogram("trace_retention_latency",
                          buckets=WIDE_HIST_BUCKETS, labels={"kind": kind})
            self._kind_hists[kind] = h
        return h

    def score(self, rec: dict) -> str | None:
        """Keep reason for one finished record dict (reads its ``data``
        summary: status / slo_violation / latency_s / tenant / kind),
        or None. Also feeds the running per-kind latency and rarity
        state — call exactly once per finished record."""
        data = rec.get("data") or {}
        kind = str(data.get("kind", ""))
        tenant = str(data.get("tenant", ""))
        latency = data.get("latency_s")
        with self._lock:
            self._seen += 1
            baseline = (self._seen % self.baseline_every) == 0
            pair = (tenant, kind)
            pair_n = self._pair_counts.get(pair, 0) + 1
            self._pair_counts[pair] = pair_n
            tail = False
            if latency is not None:
                h = self._kind_hist(kind)
                if h.count >= self.warmup:
                    tail = float(latency) >= h.percentile(self.tail_q)
                h.observe(float(latency),
                          exemplar=rec.get("trace_id"))
        if str(data.get("status", "ok")) != "ok":
            return "error"
        if data.get("slo_violation"):
            return "slo"
        if tail:
            return "tail"
        if pair_n <= self.rare_below:
            return "rare"
        if baseline:
            return "baseline"
        return None

    def stats(self) -> dict:
        with self._lock:
            return {"seen": self._seen,
                    "tenant_kind_pairs": len(self._pair_counts),
                    "kinds": sorted(self._kind_hists)}


class TraceStore:
    """Bounded per-process store of finished timeline records.

    Insertion-ordered with oldest-first eviction past ``capacity`` —
    long-lived servers keep a sliding window of recent requests, never an
    unbounded map (the exact failure mode the span tracer's
    ``max_events`` bounds against). Stores plain dicts so ``get`` replies
    are JSON-ready for the ``tracez`` verb. Thread-safe: the engine loop
    finalizes records while control handlers read them.

    With a :class:`TailRetention` attached, blind overwrite stops being
    the only policy: every finished record is scored at put-time, and
    keepers (errors, SLO breaches, latency tail, rare tenants/kinds, a
    1/N baseline) survive in a separate bounded reservoir after the
    sliding window has rolled past them — evicted keeper-priority-then-
    oldest when the reservoir fills. :meth:`pin` marks trace ids (SLO
    page-event exemplars) that must NEVER be evicted: a page alert's
    linked traces stay retrievable for as long as the process lives,
    regardless of traffic volume.
    """

    def __init__(self, capacity: int = 512,
                 retention: TailRetention | None = None,
                 keeper_capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if keeper_capacity < 1:
            raise ValueError(
                f"keeper_capacity must be >= 1, got {keeper_capacity}")
        self.capacity = int(capacity)
        self.retention = retention
        self.keeper_capacity = int(keeper_capacity)
        self._lock = threading.Lock()
        self._records: OrderedDict[str, dict] = OrderedDict()
        # key -> (record, reason); insertion-ordered so eviction can
        # take "oldest of the worst reason" deterministically.
        self._keepers: OrderedDict[str, tuple] = OrderedDict()
        self._pinned: set[str] = set()
        self.evicted = 0
        self.kept = 0
        self.keeper_evicted = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def put(self, record: "TimelineRecord | dict") -> None:
        rec = record.to_dict() if isinstance(record, TimelineRecord) else record
        tid = rec.get("trace_id")
        if not tid:
            return
        reason = (self.retention.score(rec)
                  if self.retention is not None else None)
        with self._lock:
            # A retried request revisits one trace_id on a second hop of
            # the SAME store only in single-process (LocalReplica) tests;
            # keep hops distinguishable by source-suffixing duplicates.
            key = tid
            n = 1
            while key in self._records or key in self._keepers:
                key = f"{tid}#{n}"
                n += 1
            if tid in self._pinned:
                reason = "pinned"
            if reason is not None:
                self._keepers[key] = (rec, reason)
                self.kept += 1
                self._evict_keepers_locked()
            self._records[key] = rec
            while len(self._records) > self.capacity:
                self._records.popitem(last=False)
                self.evicted += 1

    def _evict_keepers_locked(self) -> None:
        """Shrink the keeper reservoir to capacity: pinned entries are
        exempt; among the rest, drop the oldest record of the WORST
        (highest-numbered) reason present."""
        prio = TailRetention.REASON_PRIORITY
        while True:
            unpinned = [(key, reason)
                        for key, (rec, reason) in self._keepers.items()
                        if reason != "pinned"]
            if len(unpinned) <= self.keeper_capacity:
                return
            worst = max(prio.get(r, 99) for _, r in unpinned)
            for key, reason in unpinned:  # insertion order = oldest first
                if prio.get(reason, 99) == worst:
                    del self._keepers[key]
                    self.keeper_evicted += 1
                    break

    def pin(self, trace_id: str) -> bool:
        """Mark ``trace_id`` never-evictable (SLO page exemplars). Any
        hop records currently in the sliding window are promoted into
        the keeper reservoir immediately — pinning after the fact would
        otherwise race the window rolling past them. Future puts of the
        id are kept as pinned too. True when the id is now pinned (it
        need not be present yet: pin-before-arrival is how the router
        protects exemplars of requests other replicas served)."""
        tid = sanitize_trace_id(trace_id)
        if not tid:
            return False
        with self._lock:
            self._pinned.add(tid)
            for key, rec in self._records.items():
                if key == tid or key.startswith(f"{tid}#"):
                    cur = self._keepers.get(key)
                    self._keepers[key] = (rec, "pinned")
                    if cur is None:
                        self.kept += 1
            # A record already held as a keeper (e.g. as "tail") but
            # rolled out of the window upgrades in place.
            for key, (rec, reason) in list(self._keepers.items()):
                if reason != "pinned" and (
                        key == tid or key.startswith(f"{tid}#")):
                    self._keepers[key] = (rec, "pinned")
        return True

    def pinned(self) -> list[str]:
        with self._lock:
            return sorted(self._pinned)

    def get(self, trace_id: str) -> dict | None:
        """The record for ``trace_id`` (the FIRST hop when duplicated);
        see :meth:`get_all` for every hop recorded under the id."""
        hops = self.get_all(trace_id)
        return hops[0] if hops else None

    def get_all(self, trace_id: str) -> list[dict]:
        with self._lock:
            out, seen = [], set()
            for key, rec in self._records.items():
                if key == trace_id or key.startswith(f"{trace_id}#"):
                    out.append(rec)
                    seen.add(key)
            for key, (rec, _reason) in self._keepers.items():
                if key in seen:
                    continue
                if key == trace_id or key.startswith(f"{trace_id}#"):
                    out.append(rec)
            return out

    def recent(self, n: int = 20) -> list[dict]:
        n = int(n)
        if n <= 0:  # recs[-0:] would be the WHOLE store
            return []
        with self._lock:
            recs = list(self._records.values())
        return recs[-n:]

    def keepers(self, n: int | None = None, reason: str | None = None) \
            -> list[dict]:
        """Keeper-reservoir records (newest last), each annotated with
        its ``keep_reason``; optionally only one reason class."""
        with self._lock:
            out = []
            for rec, r in self._keepers.values():
                if reason is not None and r != reason:
                    continue
                annotated = dict(rec)
                annotated["keep_reason"] = r
                out.append(annotated)
        return out[-int(n):] if n else out

    def stats(self) -> dict:
        with self._lock:
            by_reason: dict[str, int] = {}
            for _rec, r in self._keepers.values():
                by_reason[r] = by_reason.get(r, 0) + 1
            out = {"records": len(self._records),
                   "capacity": self.capacity, "evicted": self.evicted}
            if self.retention is not None or self._keepers or self._pinned:
                out.update({
                    "keepers": len(self._keepers),
                    "keeper_capacity": self.keeper_capacity,
                    "keeper_evicted": self.keeper_evicted,
                    "kept": self.kept,
                    "pinned": len(self._pinned),
                    "keep_reasons": by_reason,
                })
            return out

    def export_chrome_trace(self, path: str, n: int | None = None) -> str:
        """Write the store's (most recent ``n``) records as Chrome-trace
        JSON, one lane per request hop."""
        recs = self.recent(n if n is not None else self.capacity)
        return export_chrome_trace(recs, path)


def merge_trace(trace_id: str, records) -> dict:
    """Assemble hop records (router + engines, dicts or
    :class:`TimelineRecord`) into ONE cross-process trace: the router
    record, engine hops ordered by start time, and a single
    wall-clock-sorted event list tagged with each event's source."""
    recs = []
    for r in records or []:
        if r is None:
            continue
        rec = r.to_dict() if isinstance(r, TimelineRecord) else r
        if rec.get("trace_id") == trace_id:
            recs.append(rec)
    routers = sorted((r for r in recs if r.get("role") == "router"),
                     key=lambda r: r.get("t_start", 0.0))
    engines = sorted((r for r in recs if r.get("role") == "engine"),
                     key=lambda r: r.get("t_start", 0.0))
    events = []
    for rec in recs:
        src = f"{rec.get('role', '?')}:{rec.get('source', '')}"
        for name, ts, attrs in rec.get("events", []):
            events.append([ts, src, name, attrs])
    events.sort(key=lambda e: e[0])
    return {
        "trace_id": trace_id,
        "router": routers[0] if routers else None,
        "engine_hops": engines,
        "hops": [e.get("source", "") for e in engines],
        "events": events,
    }


def chrome_trace(records) -> dict:
    """Records (or one merged trace) as Chrome ``traceEvents`` JSON —
    the format PR 2's span tracer already emits, loadable in Perfetto —
    with ONE LANE PER REQUEST HOP: ``tid`` is the hop, named
    ``<trace_id>:<role>:<source>``. Events carrying ``dur_s`` become
    complete (``X``) slices ending at their timestamp; the rest are
    instants, so a lane reads submit -> [queue] -> [prefill chunks] ->
    first_token -> done left to right."""
    recs = []
    for r in records or []:
        if isinstance(r, TimelineRecord):
            recs.append(r.to_dict())
        elif isinstance(r, dict) and "engine_hops" in r:  # merged trace
            recs.extend(x for x in
                        ([r.get("router")] + list(r.get("engine_hops", [])))
                        if x)
        elif isinstance(r, dict):
            recs.append(r)
    pid = os.getpid()
    out = []
    for tid_num, rec in enumerate(recs):
        lane = (f"{rec.get('trace_id', '?')[:16]}:{rec.get('role', '?')}"
                f":{rec.get('source', '')}")
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid_num, "args": {"name": lane}})
        for name, ts, attrs in rec.get("events", []):
            us = round(ts * 1e6, 3)
            args = dict(attrs) if attrs else {}
            dur = args.pop("dur_s", None)
            ev = {"name": name, "pid": pid, "tid": tid_num}
            if args or rec.get("trace_id"):
                args.setdefault("trace_id", rec.get("trace_id"))
                ev["args"] = args
            if dur is not None:
                ev.update(ph="X", ts=round(us - float(dur) * 1e6, 3),
                          dur=round(float(dur) * 1e6, 3))
            else:
                ev.update(ph="i", ts=us, s="t")
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_chrome_trace(records, path: str) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(records), f)
    return path
