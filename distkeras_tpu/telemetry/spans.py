"""Hierarchical wall-clock spans with Chrome-trace export.

``jax.profiler`` traces the XLA timeline; these spans trace the *host*
timeline — where a training step or serving iteration spends its wall
clock between device dispatches (data load, h2d transfer, admission,
prefill, checkpoint writes). The two views are complementary: the
profiler shows what the chip did, spans show why the chip waited.

Design constraints, in priority order:

1. **Near-zero overhead when disabled.** ``span("name")`` with no active
   tracer is one module-global read plus returning a shared no-op context
   manager — no allocation, no clock read. Hot paths (the serving decode
   loop, per-step trainer loops) keep their ``with span(...)`` lines
   unconditionally.
2. **Correct nesting across threads AND asyncio tasks.** Parent tracking
   uses a :class:`contextvars.ContextVar`, which asyncio snapshots per
   task and threading isolates per thread — a span opened inside a task
   parents to the span active when the task was created, and two
   concurrent tasks never see each other's parents. Trace *lanes* (the
   Chrome-trace ``tid``) are keyed by the running task (or thread when no
   loop is running), so interleaved tasks render as separate swimlanes
   with properly matched B/E events in each.
3. **Standard output format.** :meth:`Tracer.chrome_trace` emits the
   Chrome ``traceEvents`` JSON that chrome://tracing and Perfetto load
   directly; every ``B`` has a matching ``E`` on the same lane (spans are
   context managers, so stack discipline per lane is structural).
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from typing import Any

__all__ = [
    "Tracer",
    "span",
    "enable_tracing",
    "disable_tracing",
    "active_tracer",
]


class _NullSpan:
    """Shared do-nothing span: what ``span()`` returns while disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

# The active span in the CURRENT logical context (task- and thread-local).
_CURRENT: contextvars.ContextVar["_Span | None"] = contextvars.ContextVar(
    "distkeras_tpu_current_span", default=None
)


def _lane_key():
    """Identity of the current swimlane: the running asyncio task when
    inside a loop, else the thread. Two tasks on one thread must not share
    a lane — their B/E events interleave and would break stack nesting."""
    try:
        import asyncio

        task = asyncio.current_task()
    except RuntimeError:  # no running event loop in this thread
        task = None
    if task is not None:
        return ("task", id(task), task.get_name())
    t = threading.current_thread()
    return ("thread", t.ident, t.name)


class _Span:
    __slots__ = ("_tracer", "name", "attrs", "_token", "_lane", "_t0",
                 "_recorded")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        parent = _CURRENT.get()
        self._token = _CURRENT.set(self)
        self._lane = self._tracer._lane()
        self._t0 = time.perf_counter()
        # _record_b says whether the B event landed; when the tracer is
        # full this span is skipped wholesale (E suppressed too) so the
        # recorded stream keeps strict B/E matching per lane.
        self._recorded = self._tracer._record_b(
            self.name, self._t0, self._lane,
            parent.name if parent is not None else None, self.attrs,
        )
        return self

    def __exit__(self, *exc):
        if self._recorded:
            t1 = time.perf_counter()
            self._tracer._record_e(self.name, t1, self._lane)
        _CURRENT.reset(self._token)
        return False


class Tracer:
    """Collects span events; export with :meth:`chrome_trace` /
    :meth:`export_chrome_trace`. Thread-safe (one lock around the event
    list and lane table); cheap enough for per-iteration spans, not for
    per-element inner loops.

    ``max_events`` bounds memory on long-lived traced processes (a
    serving engine records several events per decode iteration — an
    unbounded list would grow to GBs over a multi-day run, the exact
    failure mode ServingMetrics bounds its windows against). Once full,
    NEW spans are dropped whole (their E suppressed with them, so the
    recorded prefix keeps matched B/E per lane) and counted in
    :attr:`dropped_spans`; spans already open keep their closing E.
    """

    def __init__(self, max_events: int = 1_000_000):
        self._lock = threading.Lock()
        self._events: list[tuple] = []  # (ph, name, t, lane, parent, attrs)
        self._lane_ids: dict = {}
        self._lane_names: dict[int, str] = {}
        self._max_events = int(max_events)
        self.dropped_spans = 0

    # -- recording -----------------------------------------------------------
    def span(self, name: str, **attrs: Any) -> _Span:
        return _Span(self, name, attrs)

    def _lane(self) -> int:
        key = _lane_key()
        with self._lock:
            lane = self._lane_ids.get(key)
            if lane is None:
                lane = len(self._lane_ids)
                self._lane_ids[key] = lane
                self._lane_names[lane] = f"{key[0]}:{key[2]}"
            return lane

    def _record_b(self, name, t, lane, parent, attrs) -> bool:
        with self._lock:
            # Reserve room for this span's own E (the +1): admitted spans
            # always get to close, the cap may be exceeded by the E events
            # of spans open at the moment it filled.
            if len(self._events) + 1 >= self._max_events:
                self.dropped_spans += 1
                return False
            self._events.append(("B", name, t, lane, parent, attrs))
            return True

    def _record_e(self, name, t, lane) -> None:
        with self._lock:
            self._events.append(("E", name, t, lane, None, None))

    # -- introspection / export ----------------------------------------------
    def events(self) -> list[tuple]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def chrome_trace(self) -> dict:
        """Chrome ``traceEvents`` JSON object (loadable in Perfetto and
        chrome://tracing). Timestamps are microseconds on the
        ``perf_counter`` clock; lanes become ``tid`` with a metadata name
        event each so task/thread names show on the swimlane."""
        pid = os.getpid()
        out = []
        with self._lock:
            events = list(self._events)
            lane_names = dict(self._lane_names)
            dropped = self.dropped_spans
        if dropped:
            out.append({
                "name": "dropped_spans", "ph": "M", "pid": pid, "tid": 0,
                "args": {"count": dropped},
            })
        for lane, lname in sorted(lane_names.items()):
            out.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": lane,
                "args": {"name": lname},
            })
        for ph, name, t, lane, parent, attrs in events:
            ev = {"name": name, "ph": ph, "pid": pid, "tid": lane,
                  "ts": round(t * 1e6, 3)}
            if ph == "B":
                args = dict(attrs) if attrs else {}
                if parent is not None:
                    args["parent"] = parent
                if args:
                    ev["args"] = args
            out.append(ev)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


# -- module-level switch ------------------------------------------------------

_ACTIVE: Tracer | None = None


def enable_tracing(tracer: Tracer | None = None) -> Tracer:
    """Install (and return) the process-wide tracer; subsequent
    ``span(...)`` calls record into it."""
    global _ACTIVE
    _ACTIVE = tracer if tracer is not None else Tracer()
    return _ACTIVE


def disable_tracing() -> None:
    """Back to no-op spans (already-recorded events stay on the tracer)."""
    global _ACTIVE
    _ACTIVE = None


def active_tracer() -> Tracer | None:
    return _ACTIVE


def span(name: str, **attrs: Any):
    """Context manager marking one timed region, parented to the
    enclosing span of the current task/thread. A no-op singleton when
    tracing is disabled — safe to leave on every hot path."""
    t = _ACTIVE
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)
