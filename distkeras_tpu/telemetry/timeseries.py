"""Windowed fleet-telemetry aggregates: ring-buffer timeseries, delta
encoding, and the router-side fold.

The registry (:mod:`.registry`) answers "what happened since process
start"; an SLO burn rate needs "what happened in the last N seconds",
fleet-wide. Three pieces close that gap, all dependency-free and
host-side only (nothing here may touch jax — telemetry must add zero
retraces):

- :class:`DeltaEncoder` — replica-side: snapshots a
  :class:`~distkeras_tpu.telemetry.registry.MetricsRegistry` and emits
  the compact JSON-able **delta** since its previous call (histogram
  bucket-count diffs via :func:`~distkeras_tpu.telemetry.registry.
  hist_state_delta`, counter diffs, gauge values). Zero-change metrics
  are omitted, so a quiet replica's push is a few bytes.
- :class:`TimeSeriesStore` — a per-metric ring buffer of fixed-width
  time windows. Each window keeps the histogram's NON-cumulative
  bucket counts, so a sliding p50/p99 over any span is an exact
  bucket-wise merge of windows (:meth:`TimeSeriesStore.summary`), not
  an estimate over estimates. Counters get per-window rates. Memory is
  O(metrics x capacity x buckets), independent of traffic.
- :class:`FleetAggregator` — router-side: folds replica delta payloads
  into (a) per-replica merged histograms/counters in a private
  registry (labels ``replica``/``role``, rendered on the fleet
  Prometheus page), (b) fleet-wide merged histograms (exact bucket
  merge across replicas — the true fleet p99 the pull-time JSON
  concatenation could never compute), and (c) the
  :class:`TimeSeriesStore` the SLO burn-rate engine
  (:mod:`distkeras_tpu.serving.slo`) evaluates windows from.
"""

from __future__ import annotations

import collections
import threading
import time

from distkeras_tpu.telemetry.registry import (
    MetricsRegistry,
    hist_state_delta,
    hist_state_percentile,
    merge_hist_states,
)

__all__ = ["DeltaEncoder", "TimeSeriesStore", "FleetAggregator"]


class DeltaEncoder:
    """Replica-side telemetry delta source.

    Each :meth:`delta` call snapshots every metric in ``registry`` and
    returns what changed since the previous call::

        {"seq": 3, "t": <unix-ts>,
         "hists":    {"name{a=b}": <hist delta state>, ...},
         "counters": {"name{a=b}": <increment>, ...},
         "gauges":   {"name{a=b}": <value>, ...}}

    Histogram deltas are bucket-count diffs (a restarted/reset source
    re-ships its full state — :func:`hist_state_delta` detects the
    counter going backwards). Counters ship increments; gauges ship
    current values (a gauge has no meaningful delta). Metric keys carry
    the label set inline (``name{k=v,...}``) so the receiving fold can
    reconstruct (name, labels) exactly.
    """

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.seq = 0
        self._hist_prev: dict[str, dict] = {}
        self._counter_prev: dict[str, float] = {}

    @staticmethod
    def metric_key(m) -> str:
        key = m.name
        if m.labels:
            key += "{" + ",".join(
                f"{k}={v}" for k, v in sorted(m.labels.items())) + "}"
        return key

    @staticmethod
    def parse_key(key: str) -> tuple[str, dict]:
        """Inverse of :meth:`metric_key`: ``name{k=v,...}`` back to
        (name, labels)."""
        if "{" not in key:
            return key, {}
        name, _, body = key.partition("{")
        labels = {}
        for pair in body.rstrip("}").split(","):
            if "=" in pair:
                k, _, v = pair.partition("=")
                labels[k] = v
        return name, labels

    def delta(self, full: bool = False) -> dict:
        """The changes since the previous call (everything, when
        ``full`` or on the first call)."""
        self.seq += 1
        out = {"seq": self.seq, "t": time.time(),
               "hists": {}, "counters": {}, "gauges": {}}
        for m in self.registry.collect():
            key = self.metric_key(m)
            if m.kind == "histogram":
                cur = m.state()
                prev = None if full else self._hist_prev.get(key)
                try:
                    d = hist_state_delta(cur, prev)
                except ValueError:
                    d = cur  # layout changed (re-created metric)
                self._hist_prev[key] = cur
                if d["count"] or prev is None:
                    d["help"] = m.help
                    out["hists"][key] = d
            elif m.kind == "counter":
                prev = 0.0 if full else self._counter_prev.get(key, 0.0)
                inc = float(m.value) - prev
                if inc < 0:  # reset source: full value is the delta
                    inc = float(m.value)
                self._counter_prev[key] = float(m.value)
                if inc:
                    out["counters"][key] = inc
            else:
                out["gauges"][key] = float(m.value)
        return out


class TimeSeriesStore:
    """Ring buffer of per-window aggregates, one ring per metric name.

    ``record_hist(name, delta_state)`` folds a histogram delta into the
    OPEN window's accumulator; ``record_value(name, v)`` accumulates a
    counter increment. When the clock passes a window boundary the open
    window closes into the ring: histograms keep their merged bucket
    counts (so any-span percentiles stay bucket-exact), counters keep
    (value, rate).

    ``window_s`` is the resolution; ``capacity`` windows bound memory
    and the longest queryable span. ``clock`` is injectable for exact
    tests.
    """

    def __init__(self, window_s: float = 1.0, capacity: int = 512,
                 clock=time.monotonic):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._rings: dict[str, collections.deque] = {}
        self._open: dict[str, dict] = {}  # name -> accumulating entry
        self._open_t0: float | None = None

    # -- window lifecycle ---------------------------------------------------
    def _roll_locked(self, now: float) -> None:
        if self._open_t0 is None:
            self._open_t0 = now
            return
        while now - self._open_t0 >= self.window_s:
            t1 = self._open_t0 + self.window_s
            for name, acc in self._open.items():
                ring = self._rings.setdefault(
                    name, collections.deque(maxlen=self.capacity))
                entry = {"t0": self._open_t0, "t1": t1}
                if "hist" in acc:
                    entry["hist"] = acc["hist"]
                if "value" in acc:
                    entry["value"] = acc["value"]
                    entry["rate"] = acc["value"] / self.window_s
                if "gauge" in acc:
                    entry["gauge"] = acc["gauge"]
                    entry["last"] = acc["last"]
                ring.append(entry)
            self._open = {}
            self._open_t0 = t1
            # Skip straight to the window containing `now` (quiet gaps
            # produce no empty entries — a span query just sees fewer
            # windows, and rates divide by the windows that exist).
            if now - self._open_t0 >= self.window_s:
                gap = int((now - self._open_t0) / self.window_s)
                self._open_t0 += gap * self.window_s
                break

    def _acc_locked(self, name: str) -> dict:
        now = self._clock()
        self._roll_locked(now)
        return self._open.setdefault(name, {})

    # -- recording ----------------------------------------------------------
    def record_hist(self, name: str, delta_state: dict) -> None:
        """Fold a histogram delta ``state()`` dict into the open
        window."""
        if not delta_state.get("count"):
            return
        with self._lock:
            acc = self._acc_locked(name)
            cur = acc.get("hist")
            acc["hist"] = (dict(delta_state) if cur is None
                           else merge_hist_states(cur, delta_state))

    def record_value(self, name: str, v: float) -> None:
        """Accumulate a counter increment into the open window."""
        with self._lock:
            acc = self._acc_locked(name)
            acc["value"] = acc.get("value", 0.0) + float(v)

    def record_gauge(self, name: str, v: float) -> None:
        """Fold a gauge observation into the open window, keeping the
        window max (for pressure-style signals the worst value anywhere
        in the window is the one that matters) and the last value."""
        with self._lock:
            acc = self._acc_locked(name)
            acc["gauge"] = max(acc.get("gauge", float("-inf")), float(v))
            acc["last"] = float(v)

    def flush(self) -> None:
        """Force the open window closed (tests / shutdown snapshots)."""
        with self._lock:
            self._roll_locked(self._clock() + self.window_s)

    # -- queries ------------------------------------------------------------
    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._rings)

    def query(self, name: str, span_s: float | None = None) -> list[dict]:
        """Closed windows for ``name``, oldest first, restricted to the
        trailing ``span_s`` seconds when given."""
        with self._lock:
            self._roll_locked(self._clock())
            ring = list(self._rings.get(name, ()))
        if span_s is not None:
            cutoff = self._clock() - float(span_s)
            ring = [w for w in ring if w["t1"] > cutoff]
        return ring

    def summary(self, name: str, span_s: float | None = None) -> dict | None:
        """Merged aggregate over the span's windows: for histogram
        series the bucket-exact merged counts with sliding p50/p99 and
        an event rate; for counter series the summed value and mean
        rate. ``None`` when no window holds data."""
        windows = self.query(name, span_s)
        if not windows:
            return None
        t0, t1 = windows[0]["t0"], windows[-1]["t1"]
        hists = [w["hist"] for w in windows if "hist" in w]
        out: dict = {"t0": t0, "t1": t1, "windows": len(windows),
                     "span_s": t1 - t0}
        if hists:
            merged = merge_hist_states(*hists)
            out.update({
                "count": merged["count"],
                "sum": merged["sum"],
                "p50": hist_state_percentile(merged, 50),
                "p99": hist_state_percentile(merged, 99),
                "mean": merged["sum"] / merged["count"],
                "rate": merged["count"] / max(t1 - t0, 1e-9),
                "hist": merged,
            })
        vals = [w["value"] for w in windows if "value" in w]
        if vals:
            out["value"] = sum(vals)
            out["rate"] = out["value"] / max(t1 - t0, 1e-9)
        gauges = [w["gauge"] for w in windows if "gauge" in w]
        if gauges:
            out["gauge_max"] = max(gauges)
            out["gauge_last"] = windows[-1].get("last", gauges[-1])
        return out


class FleetAggregator:
    """Router-side fold of replica telemetry deltas.

    One instance per router. ``ingest(rid, role, payload)`` folds a
    :class:`DeltaEncoder` payload:

    - histograms/counters merge into the private fleet ``registry``
      twice — once labeled ``{replica=rid, role=role}`` (the
      per-replica series on the fleet Prometheus page) and once
      labeled ``{fleet="all"}`` (the exact fleet-wide merge);
    - gauges are set per-replica only (summing gauges across a fleet
      is usually a lie — occupancy ratios don't add);
    - fleet-wide histogram deltas and counter increments feed the
      :class:`TimeSeriesStore` (``store``) the SLO engine reads.

    ``staleness_s()`` reports the mean wall-clock age of payloads at
    fold time over a sliding window — the "aggregation staleness" the
    push plane exists to drive down vs poll-time concatenation.
    """

    FLEET_LABEL = {"fleet": "all"}

    def __init__(self, store: TimeSeriesStore | None = None):
        self.registry = MetricsRegistry()
        self.store = store if store is not None else TimeSeriesStore()
        self._lock = threading.Lock()
        self._staleness = collections.deque(maxlen=256)
        self.pushes = 0
        self.push_errors = 0
        self._last_seq: dict[str, int] = {}

    def ingest(self, rid: str, role: str | None, payload: dict) -> None:
        try:
            self._ingest(rid, role or "", payload)
            with self._lock:
                self.pushes += 1
                t = payload.get("t")
                if isinstance(t, (int, float)):
                    self._staleness.append(max(0.0, time.time() - t))
        except Exception:
            with self._lock:
                self.push_errors += 1

    def _ingest(self, rid: str, role: str, payload: dict) -> None:
        self._last_seq[rid] = int(payload.get("seq") or 0)
        per_replica = {"replica": rid, "role": role}
        for key, d in (payload.get("hists") or {}).items():
            name, labels = DeltaEncoder.parse_key(key)
            help = d.get("help", "")
            buckets = tuple(d["buckets"])
            self.registry.histogram(
                name, help=help, buckets=buckets,
                **{**labels, **per_replica}).merge_state(d)
            self.registry.histogram(
                name, help=help, buckets=buckets,
                **{**labels, **self.FLEET_LABEL}).merge_state(d)
            self.store.record_hist(key, d)
        for key, inc in (payload.get("counters") or {}).items():
            name, labels = DeltaEncoder.parse_key(key)
            self.registry.counter(
                name, **{**labels, **per_replica}).inc(float(inc))
            self.registry.counter(
                name, **{**labels, **self.FLEET_LABEL}).inc(float(inc))
            self.store.record_value(key, float(inc))
        for key, v in (payload.get("gauges") or {}).items():
            name, labels = DeltaEncoder.parse_key(key)
            self.registry.gauge(
                name, **{**labels, **per_replica}).set(float(v))
            self.store.record_gauge(key, float(v))

    def forget_replica(self, rid: str) -> None:
        """Drop a dead replica's gauge series (its counted history in
        the fleet merge stays — those events happened); wired to the
        supervisor's death callbacks so a restarted incarnation's
        gauges don't coexist with the corpse's."""
        with self._lock:
            self._last_seq.pop(rid, None)
        for m in self.registry.collect():
            if m.kind == "gauge" and m.labels.get("replica") == rid:
                self.registry.remove(m)

    def fleet_hist_state(self, name: str) -> dict | None:
        """The exact fleet-wide merged state of histogram ``name``
        (label-free lookup by metric name against the fleet series)."""
        for m in self.registry.collect():
            if (m.kind == "histogram" and m.name == name
                    and m.labels.get("fleet") == "all"):
                return m.state()
        return None

    def staleness_s(self) -> float | None:
        with self._lock:
            if not self._staleness:
                return None
            return sum(self._staleness) / len(self._staleness)

    def stats(self) -> dict:
        with self._lock:
            out = {"pushes": self.pushes,
                   "push_errors": self.push_errors,
                   "replicas": dict(self._last_seq)}
        st = self.staleness_s()
        if st is not None:
            out["staleness_s"] = round(st, 6)
        return out
