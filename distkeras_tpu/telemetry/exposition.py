"""Render a :class:`MetricsRegistry` for scraping.

Two formats, zero dependencies:

- :func:`prometheus_text` — the Prometheus text exposition format
  (``# TYPE``/``# HELP`` headers, ``_bucket{le=...}``/``_sum``/``_count``
  histogram series). The serving server returns it for
  ``{"cmd": "metricsz", "format": "prometheus"}`` so a sidecar can bridge
  the JSONL protocol to a real scrape endpoint with ``nc`` and a cron;
- :func:`write_snapshot_jsonl` — one JSON line per dump, appended, for
  offline analysis next to the per-step metric streams.
"""

from __future__ import annotations

import json
import math
import time

from distkeras_tpu.telemetry.registry import MetricsRegistry

__all__ = ["prometheus_text", "write_snapshot_jsonl"]


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _fmt_labels(labels: dict, extra: dict | None = None) -> str:
    merged = {**labels, **(extra or {})}
    if not merged:
        return ""
    body = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(merged.items())
    )
    return "{" + body + "}"


def prometheus_text(registry: MetricsRegistry) -> str:
    """The registry as a Prometheus scrape page (text format 0.0.4)."""
    lines: list[str] = []
    seen_headers: set[str] = set()
    for m in registry.collect():
        if m.name not in seen_headers:
            seen_headers.add(m.name)
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
        if m.kind == "histogram":
            for bound, acc in m.cumulative_counts():
                lines.append(
                    f"{m.name}_bucket"
                    f"{_fmt_labels(m.labels, {'le': _fmt_value(bound)})}"
                    f" {acc}"
                )
            lines.append(f"{m.name}_sum{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.sum)}")
            lines.append(f"{m.name}_count{_fmt_labels(m.labels)} {m.count}")
        else:
            lines.append(f"{m.name}{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.value)}")
    return "\n".join(lines) + "\n"


def write_snapshot_jsonl(registry: MetricsRegistry, path: str) -> dict:
    """Append one timestamped snapshot line to ``path``; returns the
    snapshot written."""
    snap = {"ts": time.time(), "metrics": registry.snapshot()}
    with open(path, "a") as f:
        f.write(json.dumps(snap) + "\n")
    return snap
