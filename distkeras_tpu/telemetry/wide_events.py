"""Wide-event per-request analytics: a columnar ring + query engine.

One canonical FLAT record per finished request, emitted at done-time
(zero per-token cost), answers the question the fleet histogram plane
cannot: "WHICH tenant/kind/replica is slow, and why". Instead of a
dict per row — 40 boxed values and a heap allocation per request —
the store keeps ~40 parallel typed arrays (``array.array``) overwritten
ring-style, so 4096 requests of 40 columns cost ~1.3 MB flat and an
append is 40 array writes with no allocation in steady state.

The query engine evaluates ``filter / group_by (≤2 columns, cardinality
capped) / aggs`` (count · sum · mean · pX) in one scan. Percentile
aggregates are NOT computed from raw values alone: every group carries
a histogram ``state()`` dict on the ONE fixed
:data:`WIDE_HIST_BUCKETS` layout, so a router can fold per-replica
query results bucket-exactly with
:func:`~distkeras_tpu.telemetry.registry.merge_hist_states` — the same
merge the fleet telemetry plane already trusts — and a fleet p99 is
reproducible from raw events to within one bucket width.

Everything here is dependency-free and jax-free: the Echo replicas use
a real store for router fan-out tests, and the supervisor's crash
tooling can read a dump without an accelerator runtime.
"""

from __future__ import annotations

import math
import threading
import time
from array import array

from distkeras_tpu.telemetry.registry import (
    Histogram,
    hist_state_percentile,
    log_buckets,
    merge_hist_states,
)

__all__ = [
    "COLUMNS",
    "WIDE_HIST_BUCKETS",
    "WideEventStore",
    "parse_where",
    "parse_aggs",
    "merge_query_results",
]

# Column kinds: "i" int64, "f" float64, "s" interned low-cardinality
# string (stored as an int id column + per-column intern table), "o"
# arbitrary object (unique-per-row strings like trace ids — interning
# them would grow the table without bound, so they live in a plain
# list ring instead).
COLUMNS: tuple[tuple[str, str], ...] = (
    ("trace_id", "o"),
    ("t_done", "f"),            # wall-clock completion time (unix s)
    ("tenant", "s"),
    ("kind", "s"),              # sample | score | embed
    ("priority", "i"),
    ("replica", "s"),           # trace source, e.g. "r0"
    ("role", "s"),              # serving role (prefill/decode/mixed)
    ("mesh", "s"),              # mesh axis shape, e.g. "dp=1,tp=2"
    ("pp_stage", "i"),
    ("pp_depth", "i"),
    ("weight_version", "i"),
    ("weight_digest", "s"),
    ("prompt_tokens", "i"),
    ("output_tokens", "i"),
    ("max_new_tokens", "i"),
    ("prefix_hit_tokens", "i"),
    ("kv_blocks", "i"),
    ("forks", "i"),             # CoW fork completions delivered
    ("n", "i"),                 # requested fork count
    ("preemptions", "i"),
    ("migration", "s"),         # "" | imported | exported | failed
    ("queue_wait_s", "f"),
    ("prefill_device_s", "f"),
    ("prefill_chunks", "i"),
    ("ttft_s", "f"),
    ("latency_s", "f"),
    ("decode_iterations", "i"),
    ("spec_drafted", "i"),
    ("spec_accepted", "i"),
    ("spec_accept_rate", "f"),
    ("mask_uploads", "i"),      # constrained-decode mask uploads
    ("constrained", "i"),       # 0/1: had a decode constraint
    ("cache_overtaken", "i"),   # 0/1: prefix re-matched post-admit
    ("speculate", "i"),         # requested speculation depth
    ("temperature", "f"),
    ("status", "s"),            # ok | error | cancelled | timeout
    ("error_kind", "s"),        # typed error class name, "" when ok
    ("slo_verdict", "s"),       # ok | slow
    ("timeout_s", "f"),
    ("stream", "i"),            # 0/1: streamed delivery
)

_KINDS = dict(COLUMNS)

# Null sentinels. -1 for ints (no wide-event counter is legitimately
# negative), NaN for floats, intern id 0 (the empty string, pre-seeded)
# for interned strings, None for object columns.
_INT_NULL = -1
_FLOAT_NULL = math.nan

# The ONE bucket layout every pX aggregate uses — 1 µs to 1 M, six
# bounds per decade (~73 bounds). Fixed so that independently built
# stores (every replica, the router, offline recompute in tests) merge
# bucket-exactly; covers latencies AND token/block counts.
WIDE_HIST_BUCKETS = log_buckets(1e-6, 1e6, per_decade=6)

_AGG_FUNCS = ("count", "sum", "mean")


def parse_where(terms) -> list[tuple[str, str, object]]:
    """Parse filter terms like ``"kind=sample"`` / ``"ttft_s>0.25"``
    into ``(column, op, value)`` triples. Ops: ``= != >= <= > <``
    (ordering ops only on numeric columns). Raises ``ValueError`` on an
    unknown column, a malformed term, or an op/column-type mismatch —
    the server maps that to a typed ``bad_request`` so a CLI typo comes
    back as a message, not a silent empty result."""
    out = []
    for term in terms or ():
        term = str(term)
        for op in ("!=", ">=", "<=", "=", ">", "<"):
            if op in term:
                col, _, raw = term.partition(op)
                break
        else:
            raise ValueError(
                f"malformed where term {term!r} (want column<op>value)")
        col, raw = col.strip(), raw.strip()
        kind = _KINDS.get(col)
        if kind is None:
            raise ValueError(f"unknown column {col!r}")
        if kind in ("i", "f"):
            try:
                val: object = float(raw)
            except ValueError:
                raise ValueError(
                    f"column {col!r} is numeric; cannot compare to {raw!r}")
        else:
            if op not in ("=", "!="):
                raise ValueError(
                    f"op {op!r} needs a numeric column, {col!r} is a string")
            val = raw
        out.append((col, op, val))
    return out


def parse_aggs(specs) -> list[tuple[str, float | None, str | None]]:
    """Parse aggregate specs — ``"count"``, ``"sum:latency_s"``,
    ``"mean:ttft_s"``, ``"p99:ttft_s"`` / ``"p99.9:latency_s"`` — into
    ``(func, q, column)`` triples (``func="p"`` carries q; others
    ``q=None``). pX and sum/mean require a numeric column."""
    out = []
    for spec in specs or ("count",):
        spec = str(spec)
        func, _, col = spec.partition(":")
        func = func.strip()
        col = col.strip() or None
        if func == "count":
            if col is not None:
                raise ValueError("count takes no column")
            out.append(("count", None, None))
            continue
        if col is None:
            raise ValueError(f"agg {spec!r} needs a column (func:column)")
        kind = _KINDS.get(col)
        if kind is None:
            raise ValueError(f"unknown column {col!r}")
        if kind not in ("i", "f"):
            raise ValueError(
                f"agg {func!r} needs a numeric column, {col!r} is a string")
        if func in ("sum", "mean"):
            out.append((func, None, col))
        elif func.startswith("p"):
            try:
                q = float(func[1:])
            except ValueError:
                raise ValueError(f"unknown aggregate {func!r}")
            if not 0.0 <= q <= 100.0:
                raise ValueError(f"percentile out of range: {func!r}")
            out.append(("p", q, col))
        else:
            raise ValueError(f"unknown aggregate {func!r}")
    return out


def _agg_key(func: str, q: float | None, col: str | None) -> str:
    if func == "count":
        return "count"
    if func == "p":
        qs = f"{q:g}"
        return f"p{qs}:{col}"
    return f"{func}:{col}"


class _GroupAcc:
    """Per-group accumulator for one query: exact count/sum plus a
    fixed-layout histogram per pX aggregate (exemplared with trace ids
    so a slow group's p99 links straight to a retrievable trace)."""

    __slots__ = ("count", "sums", "hists")

    def __init__(self, aggs):
        self.count = 0
        self.sums: dict[str, list] = {}    # col -> [sum, n]
        self.hists: dict[str, Histogram] = {}
        for func, _q, col in aggs:
            if func in ("sum", "mean") and col not in self.sums:
                self.sums[col] = [0.0, 0]
            elif func == "p" and col not in self.hists:
                self.hists[col] = Histogram(
                    "wide_event_agg", buckets=WIDE_HIST_BUCKETS)


class WideEventStore:
    """Bounded columnar overwrite ring of wide events.

    ``append`` writes one slot across every parallel column under a
    lock (called once per FINISHED request — never per token) and
    self-times with one ``perf_counter`` pair so the bench probe can
    report real ns/event without wrapping the store. ``query`` scans
    the live rows oldest-first under the same lock; at the default
    4096-row capacity a full scan is sub-millisecond, which is the
    entire design argument for columnar-in-process over a log pipeline.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._count = 0            # total ever appended (monotonic)
        self._append_ns = 0        # total time inside append()
        self._cols: dict[str, object] = {}
        self._interns: dict[str, dict[str, int]] = {}
        self._rev_interns: dict[str, list[str]] = {}
        for name, kind in COLUMNS:
            if kind == "i":
                self._cols[name] = array("q", [_INT_NULL]) * self.capacity
            elif kind == "f":
                self._cols[name] = array("d", [_FLOAT_NULL]) * self.capacity
            elif kind == "s":
                self._cols[name] = array("q", [0]) * self.capacity
                self._interns[name] = {"": 0}
                self._rev_interns[name] = [""]
            else:
                self._cols[name] = [None] * self.capacity

    # -- write side ---------------------------------------------------------

    def append(self, record: dict) -> None:
        """Write one wide event. Unknown keys raise (a misspelled
        column would otherwise silently vanish); missing columns get
        the null sentinel. O(columns), no allocation beyond first-seen
        string interning."""
        t0 = time.perf_counter_ns()
        unknown = set(record) - _KINDS.keys()
        if unknown:
            raise ValueError(f"unknown wide-event columns: {sorted(unknown)}")
        with self._lock:
            slot = self._count % self.capacity
            for name, kind in COLUMNS:
                v = record.get(name)
                col = self._cols[name]
                if kind == "i":
                    col[slot] = _INT_NULL if v is None else int(v)
                elif kind == "f":
                    col[slot] = _FLOAT_NULL if v is None else float(v)
                elif kind == "s":
                    s = "" if v is None else str(v)
                    table = self._interns[name]
                    sid = table.get(s)
                    if sid is None:
                        sid = len(table)
                        table[s] = sid
                        self._rev_interns[name].append(s)
                    col[slot] = sid
                else:
                    col[slot] = v
            self._count += 1
            self._append_ns += time.perf_counter_ns() - t0

    # -- read side ----------------------------------------------------------

    def __len__(self) -> int:
        return min(self._count, self.capacity)

    def stats(self) -> dict:
        """Counters for healthz/debugz: total appends, live rows,
        overwritten rows, and measured mean append cost in ns."""
        with self._lock:
            n = self._count
            ns = self._append_ns
        return {
            "capacity": self.capacity,
            "appended": n,
            "rows": min(n, self.capacity),
            "overwritten": max(0, n - self.capacity),
            "append_ns_total": ns,
            "append_ns_mean": (ns / n if n else 0.0),
        }

    def _row_order(self) -> range:
        """Live slot indices oldest → newest (call under the lock)."""
        n = self._count
        if n <= self.capacity:
            return range(n)
        start = n % self.capacity
        # Oldest live row sits at the next overwrite slot.
        return range(start, start + self.capacity)

    def _cell(self, name: str, kind: str, slot: int):
        v = self._cols[name][slot % self.capacity]
        if kind == "i":
            return None if v == _INT_NULL else int(v)
        if kind == "f":
            return None if math.isnan(v) else float(v)
        if kind == "s":
            return self._rev_interns[name][v]
        return v

    def tail(self, n: int = 50) -> list[dict]:
        """The most recent ``n`` events as row dicts (newest LAST) —
        the export format flight-recorder dumps and crash last-words
        embed. Null cells are omitted, not emitted as None."""
        with self._lock:
            order = list(self._row_order())[-max(0, int(n)):]
            out = []
            for slot in order:
                row = {}
                for name, kind in COLUMNS:
                    v = self._cell(name, kind, slot)
                    if v is not None and v != "":
                        row[name] = v
                out.append(row)
        return out

    def query(self, where=None, group_by=None, aggs=None,
              max_groups: int = 64) -> dict:
        """One-scan filter / group / aggregate over the live ring.

        ``where``: term strings (see :func:`parse_where`) or pre-parsed
        triples. ``group_by``: ≤2 column names. ``aggs``: spec strings
        (see :func:`parse_aggs`) or pre-parsed triples. Distinct group
        keys beyond ``max_groups`` fold into one ``__other__`` bucket
        (first-seen keys win — scan order is oldest-first, so the fold
        is deterministic for a deterministic event order) and the
        result says how many keys were folded.

        Returns ``{"matched", "scanned", "group_by", "aggs",
        "groups": [{"key", "count", "aggs": {spec: payload}}]}`` where
        each pX payload carries its histogram ``state()`` on the shared
        :data:`WIDE_HIST_BUCKETS` layout — the mergeable part — plus
        the locally computed ``"value"``.
        """
        filt = (parse_where(where)
                if where and isinstance(where[0], str) else list(where or ()))
        group_by = list(group_by or ())
        if len(group_by) > 2:
            raise ValueError(
                f"group_by is capped at 2 columns, got {len(group_by)}")
        for col in group_by:
            if col not in _KINDS:
                raise ValueError(f"unknown column {col!r}")
            if _KINDS[col] == "f":
                raise ValueError(
                    f"cannot group by float column {col!r}")
        parsed_aggs = (parse_aggs(aggs)
                       if not aggs or isinstance(aggs[0], str)
                       else list(aggs))
        if max_groups < 1:
            raise ValueError(f"max_groups must be >= 1, got {max_groups}")

        groups: dict[tuple, _GroupAcc] = {}
        other: _GroupAcc | None = None
        folded_keys: set[tuple] = set()
        matched = scanned = 0
        with self._lock:
            for slot in self._row_order():
                scanned += 1
                ok = True
                for col, op, val in filt:
                    v = self._cell(col, _KINDS[col], slot)
                    if v is None:
                        ok = False
                        break
                    if op == "=":
                        ok = (v == val)
                    elif op == "!=":
                        ok = (v != val)
                    elif op == ">":
                        ok = v > val
                    elif op == "<":
                        ok = v < val
                    elif op == ">=":
                        ok = v >= val
                    else:
                        ok = v <= val
                    if not ok:
                        break
                if not ok:
                    continue
                matched += 1
                key = tuple(self._cell(c, _KINDS[c], slot)
                            for c in group_by)
                acc = groups.get(key)
                if acc is None:
                    if len(groups) < max_groups:
                        acc = groups[key] = _GroupAcc(parsed_aggs)
                    else:
                        folded_keys.add(key)
                        if other is None:
                            other = _GroupAcc(parsed_aggs)
                        acc = other
                acc.count += 1
                trace_id = self._cols["trace_id"][slot % self.capacity]
                for col, pair in acc.sums.items():
                    v = self._cell(col, _KINDS[col], slot)
                    if v is not None:
                        pair[0] += float(v)
                        pair[1] += 1
                for col, hist in acc.hists.items():
                    v = self._cell(col, _KINDS[col], slot)
                    if v is not None:
                        hist.observe(float(v), exemplar=trace_id)

        agg_keys = [_agg_key(*a) for a in parsed_aggs]
        out_groups = []
        items = list(groups.items())
        if other is not None:
            items.append((("__other__",) * max(1, len(group_by)), other))
        for key, acc in items:
            entry = {"key": dict(zip(group_by, key)) if group_by else {},
                     "count": acc.count, "aggs": {}}
            for (func, q, col), spec in zip(parsed_aggs, agg_keys):
                entry["aggs"][spec] = _finish_agg(func, q, col, acc)
            out_groups.append(entry)
        out_groups.sort(key=lambda g: (-g["count"], sorted(
            (str(k), str(v)) for k, v in g["key"].items())))
        return {
            "matched": matched,
            "scanned": scanned,
            "group_by": group_by,
            "aggs": agg_keys,
            "folded_groups": len(folded_keys),
            "groups": out_groups,
        }


def _finish_agg(func: str, q: float | None, col: str | None,
                acc: _GroupAcc) -> dict:
    """One agg payload: the computed value plus whatever mergeable
    state re-deriving it after a fleet merge needs."""
    if func == "count":
        return {"value": acc.count}
    if func in ("sum", "mean"):
        sm, n = acc.sums[col]
        value = (sm if func == "sum" else (sm / n if n else None))
        return {"value": value, "sum": sm, "n": n}
    state = acc.hists[col].state()
    value = (hist_state_percentile(state, q) if state["count"] else None)
    return {"value": value, "q": q, "state": state}


def merge_query_results(results) -> dict:
    """Fold per-replica ``query()`` results into one fleet result —
    THE code path the router's ``queryz`` fan-out uses, factored here
    so tests can assert router == this on the same inputs. Counts and
    sums add; pX aggregates merge their histogram states bucket-exactly
    via :func:`merge_hist_states` and recompute the percentile from the
    merged state, so the fleet value is exactly what one store holding
    every replica's events would have reported. Results must share
    group_by/aggs shape (they do, the router sends one spec to all)."""
    results = [r for r in results if r]
    if not results:
        raise ValueError("merge of zero query results")
    base = results[0]
    for r in results[1:]:
        if r.get("group_by") != base.get("group_by") or \
                r.get("aggs") != base.get("aggs"):
            raise ValueError("cannot merge query results of different shape")
    merged: dict[tuple, dict] = {}
    matched = scanned = folded = 0
    for r in results:
        matched += int(r.get("matched", 0))
        scanned += int(r.get("scanned", 0))
        folded += int(r.get("folded_groups", 0))
        for g in r.get("groups", ()):
            key = tuple(sorted(g["key"].items()))
            cur = merged.get(key)
            if cur is None:
                # Deep-ish copy so merging never mutates a caller's
                # payload (the router merges results it may also log).
                merged[key] = {
                    "key": dict(g["key"]),
                    "count": int(g["count"]),
                    "aggs": {spec: dict(p)
                             for spec, p in g["aggs"].items()},
                }
                continue
            cur["count"] += int(g["count"])
            for spec, payload in g["aggs"].items():
                tgt = cur["aggs"].get(spec)
                if tgt is None:
                    cur["aggs"][spec] = dict(payload)
                    continue
                if "state" in payload or "state" in tgt:
                    states = [s for s in (tgt.get("state"),
                                          payload.get("state")) if s]
                    tgt["state"] = merge_hist_states(*states)
                    tgt["q"] = payload.get("q", tgt.get("q"))
                elif "sum" in payload:
                    tgt["sum"] = float(tgt.get("sum", 0.0)) + \
                        float(payload["sum"])
                    tgt["n"] = int(tgt.get("n", 0)) + int(payload["n"])
                else:  # count
                    tgt["value"] = int(tgt.get("value", 0)) + \
                        int(payload["value"])
    for g in merged.values():
        for spec, payload in g["aggs"].items():
            if "state" in payload:
                st = payload["state"]
                payload["value"] = (
                    hist_state_percentile(st, float(payload["q"]))
                    if st and st["count"] else None)
            elif "sum" in payload:
                n = int(payload.get("n", 0))
                if spec.startswith("mean:"):
                    payload["value"] = (payload["sum"] / n if n else None)
                else:
                    payload["value"] = payload["sum"]
    groups = sorted(merged.values(),
                    key=lambda g: (-g["count"], sorted(
                        (str(k), str(v)) for k, v in g["key"].items())))
    return {
        "matched": matched,
        "scanned": scanned,
        "group_by": list(base.get("group_by") or ()),
        "aggs": list(base.get("aggs") or ()),
        "folded_groups": folded,
        "merged_from": len(results),
        "groups": groups,
    }
