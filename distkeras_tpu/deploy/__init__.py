"""Continuous deployment: the async trainers feed the serving fleet.

dist-keras's identity is asynchronous data-parallel training
(DOWNPOUR/ADAG/EASGD), and the related systems (DeepSpark, SparkNet) are
built around *periodic weight exchange at scale* — but through PR 7 the
trainers and the serving cluster still did not know about each other.
This package closes that loop, turning the repo from "a trainer and a
server" into one online-learning system:

- :class:`WeightPublisher` / :class:`PublishPolicy` — the trainer side.
  A trainer given ``--publish-dir``/``--publish-every`` atomically
  publishes stamped weight files plus a ``MANIFEST.json``
  (:func:`distkeras_tpu.checkpoint.publish_weights`) on a step or
  wall-clock cadence, optionally gated on loss improvement; watchers
  never read a torn publish.
- :class:`DeployController` — the serving side. Watches the manifest,
  **validates** each candidate (manifest/file digest agreement, leaf
  shape/dtype against the fleet's model), runs a **canary** (drain one
  replica, reload it, score a golden prompt set for finite loss,
  greedy self-parity, and a latency budget), then drives the router's
  existing zero-downtime ``rolling_reload``. A canary failure or a
  post-roll fleet regression **rolls back** to the last-good version
  and quarantines the bad file with a reason record. State (current /
  last-good / candidate, a history ring of deploy outcomes) is served
  by the router's ``deployz`` verb and ``run.py deployz``; every deploy
  is a counter + latency-histogram event in ``metricsz`` and a traced
  timeline (``tracez deploy-v<N>``).
- :mod:`.harness` — ``run.py deploy`` wiring: a ProcessReplica fleet +
  router + controller over one publish directory, and the in-process
  loop ``benchmarks/deploy_bench.py`` drives for the sustained-churn
  numbers.

The safety invariants, end to end: at most one replica is ever out of
routing (>= N-1 serving through canary and roll alike), a bad checkpoint
never reaches more than the drained canary, every response still names
the exact ``(version, digest)`` that produced it, and the compiled
decode step never retraces across any number of deploys (armed
``RecompileAuditor`` holds).
"""

from distkeras_tpu.deploy.publisher import (
    PublishPolicy,
    WeightPublisher,
    parse_publish_every,
)
from distkeras_tpu.deploy.controller import (
    CanaryFailure,
    DeployController,
    ValidationFailure,
)

__all__ = [
    "WeightPublisher",
    "PublishPolicy",
    "parse_publish_every",
    "DeployController",
    "CanaryFailure",
    "ValidationFailure",
]
