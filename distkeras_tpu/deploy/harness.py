"""Deploy-loop wiring: golden sets, golden-batch scoring, one-call setup.

``run.py deploy``, ``benchmarks/deploy_bench.py``, and the e2e tests all
need the same three pieces around a cluster: a deterministic golden
prompt set, a host-side golden-batch loss for the canary's finite-loss
check, and a :class:`~distkeras_tpu.deploy.controller.DeployController`
registered on the router (which is what makes the ``deployz`` verb
answer). This module is that shared wiring — import-light (jax loads
only inside the score fn) so the CLI can parse args without paying for
it.
"""

from __future__ import annotations

import numpy as np

__all__ = ["make_golden_prompts", "make_score_fn", "wire_controller"]


def make_golden_prompts(vocab: int, count: int = 4, length: int = 8,
                        seed: int = 0) -> list[list[int]]:
    """Deterministic golden prompt set: same seed -> same prompts, so a
    canary score is comparable deploy over deploy."""
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=(length,)).tolist()
            for _ in range(max(0, count))]


def make_score_fn(model, vocab: int, seq_len: int = 16, batch: int = 4,
                  seed: int = 0, mesh=None, warmup=None):
    """Golden-batch next-token loss under candidate weights.

    The canary's "finite loss" check: a fixed random token batch scored
    with the candidate's forward pass — NaN/inf weights (or a head that
    went numerically sideways) show up here as a non-finite loss before
    the candidate ever serves a request. The batch is deterministic per
    seed; the jitted program is cached across deploys (same shapes every
    time, so repeated canaries cost one compile total).

    ``warmup``: a variables pytree (typically the validation template)
    to score once AT BUILD TIME, off the deploy clock. Without it the
    jit's one compile (~2.3s for gpt_tiny on CPU) lands inside the
    FIRST deploy's manifest-seen→fleet-verified window — in short
    benches (2-3 deploys) that one compile was most of the recorded
    ``deploy/`` p50/p95 drift (bisected: staging hard-links and verify
    retries measure ~0; the canary's score_fn compile measured 2.3s of
    the first deploy's 2.5s).

    ``mesh``: a serving mesh — candidate leaves are then device_put
    **shard-then-place** into their logical-axis layout before the
    forward (each device gets only its slice, the arXiv:2004.13336
    rollout move), so the controller scores a model bigger than one
    chip the same way the sharded fleet serves it.
    """
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.ops.losses import categorical_crossentropy

    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(
        rng.integers(0, vocab, size=(batch, seq_len)), jnp.int32)

    param_shardings = None
    if mesh is not None and getattr(model, "boxed_init", None) is not None:
        from distkeras_tpu.parallel.sharding import (
            infer_variable_shardings,
        )

        abstract = jax.eval_shape(model.boxed_init, jax.random.PRNGKey(0))
        param_shardings = infer_variable_shardings(
            mesh, abstract)["params"]

    @jax.jit
    def _loss(variables):
        logits, _ = model.apply(variables, tokens, train=False)
        return categorical_crossentropy(logits[:, :-1], tokens[:, 1:])

    def score(variables):
        if not (isinstance(variables, dict) and "params" in variables):
            variables = {"params": variables}
        if param_shardings is not None:
            from distkeras_tpu.parallel.gspmd import place_sharded

            variables = {
                **variables,
                "params": place_sharded(variables["params"],
                                        param_shardings),
            }
        return float(_loss(variables))

    if warmup is not None:
        try:
            score(warmup)
        except Exception as e:
            # A warmup failure must never block wiring — the real
            # candidate's score reports its own error — but it must be
            # VISIBLE: a silently-skipped warmup puts the jit compile
            # back inside the first deploy's latency window, the exact
            # drift this warmup exists to prevent.
            import warnings

            warnings.warn(
                f"golden score_fn warmup failed ({e!r}); the first "
                f"deploy will pay the score compile on its clock",
                RuntimeWarning, stacklevel=2)
    return score


def wire_controller(router, watch_dir: str, *, model=None,
                    template=None, vocab: int | None = None,
                    golden_count: int = 4, golden_len: int = 8,
                    golden_new_tokens: int = 4, seed: int = 0,
                    registry=None, mesh=None, **controller_kwargs):
    """Build a :class:`DeployController` over ``router`` watching
    ``watch_dir`` and register it for the ``deployz`` verb.

    With ``model`` + ``vocab``, the golden prompt set and the
    golden-batch ``score_fn`` are built automatically (pass
    ``golden_count=0`` to skip replica-side scoring); the score fn is
    WARMED here against the template, so its one jit compile happens at
    wiring time — never inside the first deploy's latency window.
    ``template`` defaults to ``model.init(seed)`` when a model is given
    — the leaf shape/dtype validation template. ``mesh``: sharded-fleet
    deployments — golden scoring places candidates shard-then-place
    into the mesh layout (see :func:`make_score_fn`).
    """
    from distkeras_tpu.deploy.controller import DeployController

    golden = None
    score_fn = None
    if model is not None and vocab:
        golden = make_golden_prompts(vocab, count=golden_count,
                                     length=golden_len, seed=seed)
        if template is None:
            template = model.init(seed)
        score_fn = make_score_fn(model, vocab, seed=seed, mesh=mesh,
                                 warmup=template)
    controller = DeployController(
        router, watch_dir, template=template, golden_prompts=golden,
        golden_new_tokens=golden_new_tokens, score_fn=score_fn,
        registry=registry, **controller_kwargs)
    router.deploy_controller = controller
    return controller
