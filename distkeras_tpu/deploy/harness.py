"""Deploy-loop wiring: golden sets, golden-batch scoring, one-call setup.

``run.py deploy``, ``benchmarks/deploy_bench.py``, and the e2e tests all
need the same three pieces around a cluster: a deterministic golden
prompt set, a host-side golden-batch loss for the canary's finite-loss
check, and a :class:`~distkeras_tpu.deploy.controller.DeployController`
registered on the router (which is what makes the ``deployz`` verb
answer). This module is that shared wiring — import-light (jax loads
only inside the score fn) so the CLI can parse args without paying for
it.
"""

from __future__ import annotations

import numpy as np

__all__ = ["make_golden_prompts", "make_score_fn", "wire_controller"]


def make_golden_prompts(vocab: int, count: int = 4, length: int = 8,
                        seed: int = 0) -> list[list[int]]:
    """Deterministic golden prompt set: same seed -> same prompts, so a
    canary score is comparable deploy over deploy."""
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=(length,)).tolist()
            for _ in range(max(0, count))]


def make_score_fn(model, vocab: int, seq_len: int = 16, batch: int = 4,
                  seed: int = 0):
    """Golden-batch next-token loss under candidate weights.

    The canary's "finite loss" check: a fixed random token batch scored
    with the candidate's forward pass — NaN/inf weights (or a head that
    went numerically sideways) show up here as a non-finite loss before
    the candidate ever serves a request. The batch is deterministic per
    seed; the jitted program is cached across deploys (same shapes every
    time, so repeated canaries cost one compile total).
    """
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.ops.losses import categorical_crossentropy

    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(
        rng.integers(0, vocab, size=(batch, seq_len)), jnp.int32)

    @jax.jit
    def _loss(variables):
        logits, _ = model.apply(variables, tokens, train=False)
        return categorical_crossentropy(logits[:, :-1], tokens[:, 1:])

    def score(variables):
        if isinstance(variables, dict) and "params" in variables:
            return float(_loss(variables))
        return float(_loss({"params": variables}))

    return score


def wire_controller(router, watch_dir: str, *, model=None,
                    template=None, vocab: int | None = None,
                    golden_count: int = 4, golden_len: int = 8,
                    golden_new_tokens: int = 4, seed: int = 0,
                    registry=None, **controller_kwargs):
    """Build a :class:`DeployController` over ``router`` watching
    ``watch_dir`` and register it for the ``deployz`` verb.

    With ``model`` + ``vocab``, the golden prompt set and the
    golden-batch ``score_fn`` are built automatically (pass
    ``golden_count=0`` to skip replica-side scoring). ``template``
    defaults to ``model.init(seed)`` when a model is given — the leaf
    shape/dtype validation template.
    """
    from distkeras_tpu.deploy.controller import DeployController

    golden = None
    score_fn = None
    if model is not None and vocab:
        golden = make_golden_prompts(vocab, count=golden_count,
                                     length=golden_len, seed=seed)
        score_fn = make_score_fn(model, vocab, seed=seed)
        if template is None:
            template = model.init(seed)
    controller = DeployController(
        router, watch_dir, template=template, golden_prompts=golden,
        golden_new_tokens=golden_new_tokens, score_fn=score_fn,
        registry=registry, **controller_kwargs)
    router.deploy_controller = controller
    return controller
