"""DeployController: manifest watch -> validate -> canary -> roll -> verify.

The serving half of the continuous-deployment loop. One controller per
cluster router, driving the safety pipeline for every version a trainer
publishes:

1. **watch** — poll the publish directory's atomic ``MANIFEST.json``
   (:func:`distkeras_tpu.checkpoint.read_manifest`); a version newer
   than the last one processed becomes the *candidate*.
2. **validate** (host-side, no replica touched) — one read of the
   candidate file pairs arrays with their stamp; the file digest must
   agree with the manifest (a ripped copy or tampered file fails here),
   and with a ``template`` the leaf structure/shapes/dtypes must match
   the fleet's model exactly (the same check every replica's
   ``request_param_swap`` enforces, failed once centrally instead of N
   times mid-roll).
3. **canary** — borrow ONE replica: mark it DRAINING (the router stops
   routing to it; the fleet serves on N-1, same budget as a rolling
   reload), wait out its in-flight work, hot-swap it onto the candidate,
   then score the **golden prompt set** straight against that replica:
   every prompt must complete inside the latency budget, twice, with
   identical greedy output (self-parity — a deterministic decode that
   disagrees with itself is broken), and the optional ``score_fn``
   (e.g. golden-batch loss under the candidate weights) must be finite.
   On failure the canary replica is restored to the last-good weights
   and readmitted; the bad file is **quarantined** with a reason record.
4. **roll** — the router's existing zero-downtime ``rolling_reload``
   takes the vetted candidate across the fleet (the canary replica's
   second swap is a no-op-shaped idempotent reload).
5. **verify** — the roll's own per-replica outcome plus a fleet healthz:
   every replica must report the candidate's ``(version, digest)``. Any
   failure triggers **rollback** — a rolling reload back to last-good —
   and quarantine.

Every deploy is one counter + latency-histogram observation in the
metrics registry, one :class:`TimelineRecord` (trace id
``deploy-v<N>``) in the router's trace store, and one entry in the
bounded history ring the ``deployz`` verb serves.
"""

from __future__ import annotations

import asyncio
import collections
import json
import math
import os
import shutil
import time

import numpy as np

from distkeras_tpu.serving.cluster.replicas import DRAINING, READY
from distkeras_tpu.telemetry import span
from distkeras_tpu.telemetry.request_trace import TimelineRecord

__all__ = ["DeployController", "CanaryFailure", "ValidationFailure"]


class ValidationFailure(Exception):
    """Candidate rejected before touching any replica."""


class CanaryFailure(Exception):
    """Candidate rejected by the canary replica's golden-set score."""


class DeployController:
    """Watch a publish directory and safely roll each version through a
    :class:`~distkeras_tpu.serving.cluster.router.Router`'s fleet.

    ``template``: a variables pytree with the fleet model's exact leaf
    structure (e.g. ``model.init(0)`` or the boot weights) — enables the
    host-side shape/dtype validation; None skips it (the replica-side
    reload validation still applies). ``golden_prompts``: token-id lists
    scored on the canary; empty disables replica scoring (validation +
    score_fn still run). ``score_fn(variables) -> float``: optional
    host-side scalar (golden-batch loss); a non-finite value fails the
    canary. ``initial_weights``: path the fleet booted from — the
    rollback target before the first successful deploy.

    ``auto_rollback_on_verify``: roll back to last-good when the
    post-roll fleet check fails (default True).
    """

    def __init__(
        self,
        router,
        watch_dir: str,
        *,
        template=None,
        golden_prompts: list | None = None,
        golden_new_tokens: int = 4,
        canary_latency_s: float = 30.0,
        score_fn=None,
        initial_weights: str | None = None,
        poll_interval_s: float = 0.5,
        swap_timeout_s: float = 120.0,
        drain_timeout_s: float = 60.0,
        history: int = 64,
        registry=None,
        trace_store=None,
        quarantine_dir: str | None = None,
        auto_rollback_on_verify: bool = True,
        canary_tenant: str = "canary",
    ):
        self.router = router
        self.supervisor = router.supervisor
        self.watch_dir = watch_dir
        self.template = template
        self.golden_prompts = [list(map(int, p))
                               for p in (golden_prompts or [])]
        self.golden_new_tokens = int(golden_new_tokens)
        self.canary_latency_s = float(canary_latency_s)
        # The QoS identity canary traffic runs under: attributable in
        # every per-tenant metric, and deliberately outside the
        # production quota set (a quota-shed canary would veto deploys).
        self.canary_tenant = str(canary_tenant)
        self.score_fn = score_fn
        self.poll_interval_s = float(poll_interval_s)
        self.swap_timeout_s = float(swap_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.quarantine_dir = quarantine_dir or os.path.join(
            watch_dir, "quarantine")
        # Controller-owned staging: every candidate is hard-linked (or
        # copied) here before any replica touches it, and current/
        # last-good point at the STAGED files. The publisher's bounded
        # retention prunes the watch dir on ITS cadence — without
        # staging, a slow deploy (first-compile canaries, big fleets)
        # can lose the race and roll a path the pruner just deleted.
        self.staging_dir = os.path.join(watch_dir, "staging")
        self.auto_rollback_on_verify = bool(auto_rollback_on_verify)
        self.trace_store = (trace_store if trace_store is not None
                            else router.trace_store)

        # Deployed state: `current` is what the fleet serves NOW (path +
        # provenance), `last_good` the rollback target (== current after
        # a successful deploy), `candidate` the in-flight attempt.
        if initial_weights and os.path.exists(initial_weights):
            # Boot weights get staged too: the first rollback target
            # must outlive the publisher's pruner exactly like any
            # deployed version.
            try:
                initial_weights = self._stage(initial_weights)
            except OSError:
                pass
        self.current: dict | None = (
            self._prov_of(initial_weights) if initial_weights else None)
        self.last_good: dict | None = (dict(self.current)
                                       if self.current else None)
        self.candidate: dict | None = None
        self.history: collections.deque = collections.deque(maxlen=history)
        self.quarantined: collections.deque = collections.deque(maxlen=32)
        self._seen_version = (self.current or {}).get("version", 0) or 0
        self._stopping = asyncio.Event()
        self.deploys = 0
        self.canary_failures = 0
        self.validation_failures = 0
        self.rollbacks = 0
        self._c_deploys = self._c_canary_fail = self._c_rollbacks = None
        self._c_validate_fail = self._h_latency = self._g_version = None
        if registry is not None:
            self._c_deploys = registry.counter(
                "deploy_total", help="successful fleet deploys")
            self._c_canary_fail = registry.counter(
                "deploy_canary_failures_total",
                help="candidates rejected by the canary replica")
            self._c_validate_fail = registry.counter(
                "deploy_validation_failures_total",
                help="candidates rejected by host-side validation")
            self._c_rollbacks = registry.counter(
                "deploy_rollbacks_total",
                help="rolls reverted to the last-good version")
            self._h_latency = registry.histogram(
                "deploy_latency_seconds",
                help="manifest-seen to fleet-verified deploy latency",
                buckets=(0.5, 1, 2, 5, 10, 30, 60, 120, 300))
            self._g_version = registry.gauge(
                "deploy_current_version",
                help="weight version the controller last verified fleet-"
                     "wide")
            if self.current and self.current.get("version"):
                self._g_version.set(self.current["version"])

    # -- helpers ------------------------------------------------------------
    def _stage(self, path: str) -> str:
        """Pin ``path`` into the staging dir (hard link when the
        filesystem allows, byte copy otherwise) and return the staged
        path. Raises OSError if the source vanished — the publisher
        pruned it before we could pin it, which IS a missed candidate
        (the next publish retries)."""
        os.makedirs(self.staging_dir, exist_ok=True)
        dest = os.path.join(self.staging_dir, os.path.basename(path))
        if os.path.exists(dest):
            return dest
        try:
            os.link(path, dest)
        except OSError:
            shutil.copy2(path, dest)
        return dest

    def _prune_staging(self) -> None:
        """Drop staged files no deploy state references (best-effort)."""
        keep = {os.path.basename(s["path"])
                for s in (self.current, self.last_good, self.candidate)
                if s and s.get("path")}
        try:
            names = os.listdir(self.staging_dir)
        except OSError:
            return
        for name in names:
            if name not in keep:
                try:
                    os.unlink(os.path.join(self.staging_dir, name))
                except OSError:
                    pass

    @staticmethod
    def _prov_of(path: str) -> dict:
        from distkeras_tpu.checkpoint import weights_provenance

        try:
            return weights_provenance(path)
        except OSError:
            return {"version": 0, "digest": None, "path": path}

    def stop(self) -> None:
        self._stopping.set()

    # -- watch loop ---------------------------------------------------------
    async def run(self) -> None:
        """Poll the manifest until :meth:`stop`; deploy every new
        version exactly once (failures are recorded, not retried — the
        NEXT publish is the retry, which is what a trainer on a cadence
        provides for free)."""
        from distkeras_tpu.checkpoint import read_manifest

        while not self._stopping.is_set():
            manifest = read_manifest(self.watch_dir)
            if (manifest and int(manifest.get("version", 0))
                    > self._seen_version):
                await self.deploy(manifest)
            try:
                await asyncio.wait_for(self._stopping.wait(),
                                       self.poll_interval_s)
            except asyncio.TimeoutError:
                pass

    async def poll_once(self) -> dict | None:
        """One watch-loop iteration (tests and benches drive this for
        deterministic pacing). Returns the deploy outcome, or None when
        the manifest holds nothing new."""
        from distkeras_tpu.checkpoint import read_manifest

        manifest = read_manifest(self.watch_dir)
        if manifest and int(manifest.get("version", 0)) > self._seen_version:
            return await self.deploy(manifest)
        return None

    # -- the deploy pipeline ------------------------------------------------
    async def deploy(self, manifest: dict) -> dict:
        """Run one candidate through validate -> canary -> roll ->
        verify. Returns (and records) the outcome entry."""
        version = int(manifest.get("version", 0))
        path = orig_path = manifest.get("path")
        t0 = time.monotonic()
        trace = TimelineRecord(f"deploy-v{version}", "deploy", "controller")
        trace.event("manifest_seen", version=version,
                    digest=manifest.get("digest"), step=manifest.get("step"),
                    loss=manifest.get("loss"))
        self._seen_version = version
        # Pin the candidate NOW: from here on the pipeline (validate,
        # canary, roll, a later rollback) reads the controller's staged
        # copy, immune to the publisher pruning the watch dir mid-deploy.
        if path and os.path.exists(path):
            try:
                staged = self._stage(path)
                if staged != path:
                    trace.event("staged", path=os.path.basename(staged))
                path = staged
                manifest = {**manifest, "path": path}
            except OSError:
                pass  # source pruned under us: the exists-check below
                # turns this into a clean validation failure
        self.candidate = {"version": version,
                          "digest": manifest.get("digest"), "path": path}
        entry = {"version": version, "digest": manifest.get("digest"),
                 "path": path, "step": manifest.get("step"),
                 "loss": manifest.get("loss"), "t": time.time()}
        try:
            with span("deploy", version=version):
                await self._deploy_inner(manifest, trace, entry)
            entry["status"] = "deployed"
            self.deploys += 1
            if self._c_deploys is not None:
                self._c_deploys.inc()
            if self._g_version is not None:
                self._g_version.set(version)
            self.current = {"version": version,
                            "digest": manifest.get("digest"), "path": path}
            self.last_good = dict(self.current)
            trace.data["status"] = "deployed"
        except ValidationFailure as e:
            self.validation_failures += 1
            if self._c_validate_fail is not None:
                self._c_validate_fail.inc()
            entry["status"] = "validation_failed"
            entry["reason"] = str(e)
            trace.event("validation_failed", reason=str(e))
            trace.data["status"] = "validation_failed"
            self._quarantine(path, version, f"validation: {e}",
                             orig_path=orig_path)
        except CanaryFailure as e:
            self.canary_failures += 1
            if self._c_canary_fail is not None:
                self._c_canary_fail.inc()
            entry["status"] = "canary_rejected"
            entry["reason"] = str(e)
            trace.event("canary_rejected", reason=str(e))
            trace.data["status"] = "canary_rejected"
            self._quarantine(path, version, f"canary: {e}",
                             orig_path=orig_path)
        except Exception as e:
            # Reached the roll and failed -> rolled back (file suspect:
            # quarantine). Never reached a replica (e.g. fleet down) ->
            # plain failure; the file stays publishable so the trainer's
            # next manifest (or an operator retry) can deploy it.
            rolled = "rolled" in entry
            entry["status"] = "rolled_back" if rolled else "failed"
            entry["reason"] = str(e)
            trace.event("rolled_back" if rolled else "failed",
                        reason=str(e))
            trace.data["status"] = entry["status"]
            if rolled:
                self._quarantine(path, version, f"post-roll: {e}",
                                 orig_path=orig_path)
        finally:
            self.candidate = None
            self._prune_staging()
            latency = time.monotonic() - t0
            entry["latency_s"] = round(latency, 3)
            # Histogram = manifest-seen -> fleet-VERIFIED, deployed
            # outcomes only: a trainer churning out bad checkpoints
            # (rejected host-side in milliseconds) must not drag the
            # p95 an operator alerts on down below the real deploys.
            # Per-outcome latency survives in the history ring.
            # .get: a BaseException (task cancelled mid-deploy) reaches
            # this finally with no status set and must not be masked.
            if (self._h_latency is not None
                    and entry.get("status") == "deployed"):
                self._h_latency.observe(latency)
            trace.data["version"] = version
            trace.data["latency_s"] = round(latency, 3)
            trace.event("done", status=entry.get("status"), dur_s=latency)
            if self.trace_store is not None:
                self.trace_store.put(trace)
            self.history.append(entry)
        return entry

    async def _deploy_inner(self, manifest: dict, trace: TimelineRecord,
                            entry: dict) -> None:
        path = manifest.get("path")
        if not path or not os.path.exists(path):
            raise ValidationFailure(f"manifest names a missing file: "
                                    f"{path!r}")
        variables = await asyncio.get_running_loop().run_in_executor(
            None, self._validate_sync, manifest)
        trace.event("validated")
        canary_rid = await self._canary(path, variables, trace, entry)
        await self._roll_and_verify(manifest, canary_rid, trace, entry)

    # -- stage 2: host-side validation --------------------------------------
    def _validate_sync(self, manifest: dict):
        """Executor half of validation: ONE read pairs arrays with their
        stamp; digest and (with a template) leaf shapes/dtypes checked
        before any replica is touched. Returns the loaded variables (the
        canary's score_fn reuses them — no second read)."""
        from distkeras_tpu.checkpoint import load_weights_file_with_provenance

        path = manifest["path"]
        try:
            variables, prov = load_weights_file_with_provenance(path)
        except Exception as e:
            raise ValidationFailure(f"unreadable weights file: {e!r}") from e
        want = manifest.get("digest")
        if want and prov.get("digest") != want:
            raise ValidationFailure(
                f"digest mismatch: manifest says {want}, file bytes are "
                f"{prov.get('digest')} (torn or tampered publish)")
        if self.template is not None:
            import jax

            tmpl = self.template
            if isinstance(tmpl, dict) and "params" in tmpl:
                tmpl_tree = tmpl
            else:
                tmpl_tree = {"params": tmpl}
            cand = (variables if isinstance(variables, dict)
                    and "params" in variables else {"params": variables})
            want_leaves = jax.tree.leaves(tmpl_tree)
            got_leaves = jax.tree.leaves(cand)
            if len(got_leaves) != len(want_leaves):
                raise ValidationFailure(
                    f"candidate has {len(got_leaves)} leaves; fleet model "
                    f"has {len(want_leaves)}")
            for i, (a, b) in enumerate(zip(got_leaves, want_leaves)):
                a, b = np.asarray(a), np.asarray(b)
                if a.shape != b.shape or a.dtype != b.dtype:
                    raise ValidationFailure(
                        f"candidate leaf {i} is {a.dtype}{a.shape}; fleet "
                        f"model expects {b.dtype}{b.shape}")
        return variables

    # -- stage 3: canary -----------------------------------------------------
    def _pick_canary(self):
        ready = [r for r in self.supervisor.replicas.values()
                 if r.status == READY]
        if len(ready) < 1:
            raise RuntimeError("no READY replica to canary on")
        # Least outstanding work thrown out of routing; ties break on rid
        # so repeated deploys spread deterministically.
        return min(ready, key=lambda r: (r.outstanding, r.rid))

    async def _canary(self, path: str, variables, trace: TimelineRecord,
                      entry: dict) -> str:
        """Drain one replica, reload it onto the candidate, score the
        golden set against it. Returns the canary rid on success;
        restores the replica and raises :class:`CanaryFailure` on any
        miss. The replica is readmitted READY either way."""
        # Host-side score first: it needs no replica, so a non-finite
        # golden loss never even drains one.
        if self.score_fn is not None:
            try:
                score = float(await asyncio.get_running_loop()
                              .run_in_executor(None, self.score_fn,
                                               variables))
            except CanaryFailure:
                raise
            except Exception as e:
                raise CanaryFailure(f"score_fn failed: {e!r}") from e
            entry["golden_score"] = (score if math.isfinite(score)
                                     else str(score))
            trace.event("scored", score=entry["golden_score"])
            if not math.isfinite(score):
                raise CanaryFailure(
                    f"golden score is not finite: {score}")
        info = self._pick_canary()
        trace.event("canary_drain", replica=info.rid)
        entry["canary"] = info.rid
        info.status = DRAINING
        try:
            deadline = time.monotonic() + self.drain_timeout_s
            while info.outstanding > 0:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"canary drain timed out with {info.outstanding} "
                        f"outstanding")
                await asyncio.sleep(0.01)
            with span("canary_reload", replica=info.rid):
                rep = await self.router._backend_control(
                    info, {"cmd": "reload", "weights": path,
                           "timeout": self.swap_timeout_s},
                    timeout=self.swap_timeout_s + 10.0)
            if "error" in rep:
                raise CanaryFailure(
                    f"canary replica {info.rid} refused the reload: "
                    f"{rep['error']}")
            trace.event("canary_reloaded", replica=info.rid)
            try:
                results = await self._score_golden(info, trace)
            except CanaryFailure:
                await self._restore_canary(info, trace)
                raise
            entry["canary_golden"] = results
            trace.event("canary_passed", prompts=len(self.golden_prompts))
            return info.rid
        except (OSError, asyncio.TimeoutError, ValueError,
                RuntimeError) as e:
            # Transport/drain trouble around the canary is a candidate
            # rejection too — with the replica restored if we got as far
            # as swapping it.
            await self._restore_canary(info, trace)
            raise CanaryFailure(str(e)) from e
        finally:
            if info.status == DRAINING:
                info.status = READY

    async def _score_golden(self, info, trace: TimelineRecord) -> dict:
        """Golden-set scoring against the (drained) canary replica:
        every prompt completes twice within the latency budget with
        identical greedy output."""
        latencies = []
        for i, prompt in enumerate(self.golden_prompts):
            first, t_first = await self._generate_direct(info, prompt)
            second, t_second = await self._generate_direct(info, prompt)
            latencies.append(max(t_first, t_second))
            if first != second:
                raise CanaryFailure(
                    f"golden prompt {i}: greedy self-parity violated "
                    f"({first[:8]}... vs {second[:8]}...)")
            worst = max(t_first, t_second)
            if worst > self.canary_latency_s:
                raise CanaryFailure(
                    f"golden prompt {i}: {worst:.3f}s exceeds the "
                    f"{self.canary_latency_s}s canary latency budget")
        return {"prompts": len(self.golden_prompts),
                "max_latency_s": round(max(latencies), 4) if latencies
                else None}

    async def _generate_direct(self, info, prompt: list) -> tuple[list,
                                                                  float]:
        """One greedy generation straight against the canary replica
        over a :class:`ServingClient` pointed at its own port (bypasses
        routing — the replica is DRAINING, deliberately invisible to
        the router's pick). No transport retry: a canary that needs one
        has failed."""
        from distkeras_tpu.serving.client import ServingClient
        from distkeras_tpu.serving.scheduler import ServingError

        budget = self.canary_latency_s + 5.0
        t0 = time.monotonic()
        try:
            async with ServingClient(info.host, info.port,
                                     max_retries=0) as client:
                done = await asyncio.wait_for(
                    client.generate(prompt, self.golden_new_tokens,
                                    temperature=0.0,
                                    tenant=self.canary_tenant,
                                    trace_id=f"canary-{info.rid}"),
                    budget)
        except asyncio.TimeoutError as e:
            raise CanaryFailure(
                f"canary stream stalled past {budget:.1f}s") from e
        except ServingError as e:
            raise CanaryFailure(
                f"canary errored on a golden prompt: {e} "
                f"({getattr(e, 'code', 'error')})") from e
        except (OSError, ConnectionError, ValueError) as e:
            raise CanaryFailure(f"canary unreachable: {e}") from e
        return list(done.get("tokens", [])), time.monotonic() - t0

    async def _restore_canary(self, info, trace: TimelineRecord) -> None:
        """Put the canary replica back on the last-good weights. With no
        last-good FILE (inline-booted fleet, nothing deployed yet) the
        replica is killed instead — the supervisor's restart brings back
        a fresh factory-boot replica, which IS the pre-deploy state."""
        target = (self.last_good or {}).get("path")
        if target and os.path.exists(target):
            try:
                rep = await self.router._backend_control(
                    info, {"cmd": "reload", "weights": target,
                           "timeout": self.swap_timeout_s},
                    timeout=self.swap_timeout_s + 10.0)
                if "error" not in rep:
                    trace.event("canary_restored", replica=info.rid,
                                weights=os.path.basename(target))
                    return
            except (OSError, ValueError, asyncio.TimeoutError):
                pass
        # No restorable file, or the restore itself failed: recycle the
        # replica through the supervisor (kill + fresh factory boot =
        # the pre-deploy state) rather than readmit bad weights.
        trace.event("canary_recycled", replica=info.rid)
        self.supervisor._on_dead(info, "deploy canary rollback")

    # -- stages 4+5: roll and verify ----------------------------------------
    async def _roll_and_verify(self, manifest: dict, canary_rid: str,
                               trace: TimelineRecord, entry: dict) -> None:
        path = manifest["path"]
        with span("rolling_reload", version=manifest.get("version")):
            rep = await self.router.rolling_reload(
                {"weights": path, "timeout": self.swap_timeout_s,
                 "drain_timeout": self.drain_timeout_s})
        roll = rep.get("reload", {})
        trace.event("rolled", reloaded=roll.get("reloaded"),
                    failed=list(roll.get("failed", {})) or None)
        entry["rolled"] = roll.get("reloaded", [])
        # Per-replica before/after stamps from the roll's own reply —
        # the deployz history shows each replica's version movement
        # without any extra fan-out.
        if roll.get("replicas"):
            entry["replicas_moved"] = roll["replicas"]
        if roll.get("failed"):
            await self._rollback(trace)
            raise RuntimeError(f"roll failed on {sorted(roll['failed'])}: "
                               f"{roll['failed']}")
        ok, detail = await self._verify_fleet(manifest)
        trace.event("verified", ok=ok)
        entry["verify"] = detail
        if not ok:
            if self.auto_rollback_on_verify:
                await self._rollback(trace)
            raise RuntimeError(f"post-roll verify failed: {detail}")

    async def _verify_fleet(self, manifest: dict,
                            attempts: int = 3) -> tuple[bool, dict]:
        """Fleet healthz: no routable replica may report any OTHER
        (version, digest), and at least one must confirm the
        candidate's. A probe that merely timed out (a loaded host, not a
        wrong version) is retried, then tolerated: an unreachable
        replica is the supervisor's problem — it gets restarted onto
        ``current_weights``, which the roll just moved to the candidate
        — whereas a CONFLICTING version is a failed roll and always
        fails verify.

        Sharded fleets: each replica's healthz carries its ``mesh``
        (axis sizes); a fleet whose routable replicas disagree on mesh
        shape is failed like a version conflict — a restart that came
        back unsharded (or on a different tp) would serve the same
        weights with a different memory/latency envelope than the
        canary vetted, silently."""
        want = f"{manifest.get('version')}:{manifest.get('digest')}"
        detail: dict = {"want": want}
        for attempt in range(attempts):
            health = (await self.router._control({"cmd": "healthz"})).get(
                "healthz", {})
            router_h = health.get("router", {})
            versions = router_h.get("weight_versions", {})
            routable = sum(1 for r in health.get("replicas", {}).values()
                           if r.get("status") in (READY, DRAINING))
            meshes: dict[str, str] = {}
            for rid, r in health.get("replicas", {}).items():
                if r.get("status") not in (READY, DRAINING):
                    continue
                sub = r.get("healthz")
                if isinstance(sub, dict):
                    axes = (sub.get("mesh") or {}).get("axes")
                    meshes[rid] = (json.dumps(axes, sort_keys=True)
                                   if axes else "unsharded")
            detail = {"weight_versions": versions,
                      "replicas_ready": router_h.get("replicas_ready"),
                      "want": want}
            if meshes:
                detail["meshes"] = meshes
            mesh_conflict = len(set(meshes.values())) > 1
            conflict = any(k != want for k in versions) or mesh_conflict
            confirmed = versions.get(want, 0)
            if not conflict and confirmed >= routable and routable >= 1:
                return True, detail
            if conflict or attempt == attempts - 1:
                if not conflict and confirmed >= 1:
                    detail["unconfirmed"] = routable - confirmed
                    return True, detail
                return False, detail
            await asyncio.sleep(0.5)
        return False, detail

    async def _rollback(self, trace: TimelineRecord) -> None:
        target = (self.last_good or {}).get("path")
        self.rollbacks += 1
        if self._c_rollbacks is not None:
            self._c_rollbacks.inc()
        if not target or not os.path.exists(target):
            trace.event("rollback_impossible")
            return
        with span("deploy_rollback", weights=target):
            rep = await self.router.rolling_reload(
                {"weights": target, "timeout": self.swap_timeout_s,
                 "drain_timeout": self.drain_timeout_s})
        trace.event("rollback",
                    weights=os.path.basename(target),
                    failed=list(rep.get("reload", {}).get("failed", {}))
                    or None)

    # -- quarantine ----------------------------------------------------------
    def _quarantine(self, path: str | None, version: int, reason: str,
                    orig_path: str | None = None) -> None:
        """Move a rejected candidate (the controller's staged copy) into
        the quarantine dir — the retention pruner must never make a bad
        file *disappear* before an operator reads it — with a reason
        record beside it. The publisher's original in the watch dir (a
        second name for the same bytes when staging hard-linked) is
        removed so a known-bad file doesn't linger where the next reader
        might trust it."""
        record = {"version": version, "reason": reason, "t": time.time(),
                  "path": path}
        try:
            if path and os.path.exists(path):
                os.makedirs(self.quarantine_dir, exist_ok=True)
                dest = os.path.join(self.quarantine_dir,
                                    os.path.basename(path))
                shutil.move(path, dest)
                record["quarantined_to"] = dest
                with open(dest + ".reason.json", "w") as f:
                    json.dump(record, f)
        except OSError as e:
            record["quarantine_error"] = str(e)
        if orig_path and orig_path != path:
            try:
                os.unlink(orig_path)
            except OSError:
                pass
        self.quarantined.append(record)

    # -- introspection -------------------------------------------------------
    def deployz(self) -> dict:
        """The controller state page the router's ``deployz`` verb (and
        ``run.py deployz``) serves."""
        return {
            "watch_dir": self.watch_dir,
            "current": self.current,
            "last_good": self.last_good,
            "candidate": self.candidate,
            "seen_version": self._seen_version,
            "counters": {
                "deploys": self.deploys,
                "canary_failures": self.canary_failures,
                "validation_failures": self.validation_failures,
                "rollbacks": self.rollbacks,
            },
            "golden_prompts": len(self.golden_prompts),
            "poll_interval_s": self.poll_interval_s,
            "history": list(self.history),
            "quarantined": list(self.quarantined),
        }
