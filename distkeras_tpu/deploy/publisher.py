"""Trainer-side weight publishing: cadence + atomic manifest.

The publisher is deliberately passive — trainers drive it (a per-step
call in the step-loop trainers, a background thread over the PS center
in the async family), it decides *whether* this moment is a publish
point and performs the atomic write. Publishing must never take down
training: filesystem failures are counted and logged once, not raised
into the step loop.

Cadence semantics (:class:`PublishPolicy`): a publish is DUE when
``every_steps`` steps or ``every_seconds`` seconds have passed since the
last publish (either alone suffices; the first call is always due so a
short run still leaves one manifest behind). ``min_improvement`` is the
optional metric gate: when set, a due publish additionally requires the
observed loss to have improved by at least that much over the best loss
already published — the knob that keeps a plateaued run from churning
the serving fleet with equivalent checkpoints. The loss is only
*evaluated* when the cadence is due (``loss_fn`` is lazy), so the gate
costs nothing per step.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

__all__ = ["PublishPolicy", "WeightPublisher", "parse_publish_every"]

log = logging.getLogger(__name__)


def parse_publish_every(spec: str | int | float) -> "PublishPolicy":
    """Parse the CLI form of a publish cadence: ``"30s"`` / ``"2.5s"``
    (wall-clock seconds) or ``"200"`` (steps / PS commits)."""
    if isinstance(spec, (int, float)):
        return PublishPolicy(every_steps=int(spec))
    s = str(spec).strip().lower()
    if s.endswith("s"):
        seconds = float(s[:-1])
        if seconds <= 0:
            raise ValueError(f"publish-every seconds must be > 0: {spec!r}")
        return PublishPolicy(every_seconds=seconds)
    steps = int(s)
    if steps <= 0:
        raise ValueError(f"publish-every steps must be > 0: {spec!r}")
    return PublishPolicy(every_steps=steps)


class PublishPolicy:
    """When to publish: step cadence, wall-clock cadence, loss gate."""

    def __init__(self, every_steps: int | None = None,
                 every_seconds: float | None = None,
                 min_improvement: float | None = None):
        if every_steps is None and every_seconds is None:
            raise ValueError(
                "PublishPolicy needs every_steps and/or every_seconds")
        self.every_steps = int(every_steps) if every_steps else None
        self.every_seconds = float(every_seconds) if every_seconds else None
        self.min_improvement = (float(min_improvement)
                                if min_improvement else None)
        self._last_step: int | None = None
        self._last_time: float | None = None
        self._best_loss: float | None = None

    def due(self, step: int | None, now: float) -> bool:
        """Cadence check only (cheap, per step). First call is due."""
        if self._last_time is None:
            return True
        if (self.every_steps is not None and step is not None
                and self._last_step is not None
                and step - self._last_step >= self.every_steps):
            return True
        if (self.every_seconds is not None
                and now - self._last_time >= self.every_seconds):
            return True
        return False

    def gate(self, loss: float | None) -> bool:
        """The optional metric gate, evaluated only when due: with
        ``min_improvement`` set, a due publish is vetoed unless ``loss``
        improved enough on the best already-published loss (an unknown
        loss passes — the gate is an optimization, not a correctness
        fence)."""
        if self.min_improvement is None or loss is None:
            return True
        if self._best_loss is None:
            return True
        return self._best_loss - float(loss) >= self.min_improvement

    def note_published(self, step: int | None, now: float,
                       loss: float | None) -> None:
        self._last_step = step
        self._last_time = now
        if loss is not None:
            loss = float(loss)
            if self._best_loss is None or loss < self._best_loss:
                self._best_loss = loss


class WeightPublisher:
    """Atomic stamped publishes into one directory, on a policy.

    ``directory`` is the publish directory a
    :class:`~distkeras_tpu.deploy.controller.DeployController` watches;
    ``keep`` bounds retained old versions (see
    :func:`distkeras_tpu.checkpoint.publish_weights`). ``registry`` adds
    ``weights_published_total`` / ``weights_publish_failures_total``
    counters and a ``weights_published_version`` gauge.

    Thread-safe: the async trainers publish from a dedicated thread
    while the driver thread may take a final snapshot at exit.
    """

    def __init__(self, directory: str, policy: PublishPolicy | None = None,
                 *, keep: int = 5, registry=None):
        self.directory = directory
        self.policy = policy
        self.keep = int(keep)
        self.published = 0
        self.failures = 0
        self.last_manifest: dict | None = None
        self._lock = threading.Lock()
        self._c_published = self._c_failures = self._g_version = None
        if registry is not None:
            self._c_published = registry.counter(
                "weights_published_total",
                help="stamped weight files published to the publish dir")
            self._c_failures = registry.counter(
                "weights_publish_failures_total",
                help="publishes that failed (filesystem errors; training "
                     "continues)")
            self._g_version = registry.gauge(
                "weights_published_version",
                help="version of the most recent successful publish")

    def maybe_publish(self, variables_fn: Callable[[], Any],
                      step: int | None = None,
                      loss_fn: Callable[[], float | None] | None = None,
                      ) -> dict | None:
        """Publish if the policy says so. ``variables_fn`` and
        ``loss_fn`` are lazy — neither runs unless the cadence is due
        (the per-step cost of an idle publisher is two comparisons).
        Returns the manifest on publish, None otherwise."""
        if self.policy is None:
            return None
        with self._lock:
            now = time.monotonic()
            if not self.policy.due(step, now):
                return None
            loss = None
            if loss_fn is not None:
                try:
                    loss = loss_fn()
                except Exception:
                    loss = None
            if not self.policy.gate(loss):
                # A vetoed cadence point still resets the clock —
                # otherwise every subsequent step re-evaluates the loss.
                self.policy.note_published(step, now, None)
                return None
            try:
                variables = variables_fn()
            except Exception:
                self._note_failure("variables_fn failed")
                self.policy.note_published(step, now, None)
                return None
            manifest = self._publish_locked(variables, step, loss)
            # A FAILED publish must not poison the loss gate: recording
            # its loss as "best published" would veto every later
            # publish against a checkpoint that never landed. The
            # cadence clock still resets (no disk-hammering retry loop;
            # the next due point retries).
            self.policy.note_published(step, now,
                                       loss if manifest else None)
            return manifest

    def publish(self, variables: Any, step: int | None = None,
                loss: float | None = None) -> dict | None:
        """Unconditional publish (final-at-exit snapshots, benches)."""
        with self._lock:
            manifest = self._publish_locked(variables, step, loss)
            if self.policy is not None:
                self.policy.note_published(step, time.monotonic(),
                                           loss if manifest else None)
            return manifest

    def _publish_locked(self, variables: Any, step: int | None,
                        loss: float | None) -> dict | None:
        from distkeras_tpu.checkpoint import publish_weights

        meta: dict = {}
        if step is not None:
            meta["step"] = int(step)
        if loss is not None:
            meta["loss"] = float(loss)
        try:
            manifest = publish_weights(self.directory, variables,
                                       meta=meta, keep=self.keep)
        except Exception as e:
            # Exception, not just OSError: the contract is that
            # publishing NEVER takes down (or silently stops inside)
            # training — a serialization surprise must be counted and
            # logged exactly like a full disk.
            self._note_failure(e)
            return None
        self.published += 1
        self.last_manifest = manifest
        if self._c_published is not None:
            self._c_published.inc()
        if self._g_version is not None:
            self._g_version.set(manifest["version"])
        return manifest

    def _note_failure(self, err) -> None:
        self.failures += 1
        if self._c_failures is not None:
            self._c_failures.inc()
        if self.failures == 1:
            log.exception("weight publish to %s failed", self.directory)
        else:
            log.warning("weight publish to %s failed (%d so far): %s",
                        self.directory, self.failures, err)

    def stats(self) -> dict:
        with self._lock:
            out = {"directory": self.directory, "published": self.published,
                   "failures": self.failures}
            if self.last_manifest:
                out["last_version"] = self.last_manifest.get("version")
                out["last_digest"] = self.last_manifest.get("digest")
            return out
