// fastdata: native data-plane helpers for dist-keras-tpu.
//
// The reference framework assembled minibatches row-by-row in Python inside
// Spark executors (its data-path bottleneck; SURVEY §3.1 hot loop). Here the
// host-side data plane is native: CSV parsing into columnar float32 buffers,
// permutation gather for shuffled epochs, and strided minibatch packing —
// all operating on raw buffers shared with numpy through ctypes (no copies
// besides the output writes, no Python objects per row).
//
// Build: make -C native   (produces libfastdata.so; loaded via ctypes by
// distkeras_tpu/data/native.py, with a pure-numpy fallback when absent).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstdio>
#include <cmath>

extern "C" {

// Parse a headerless CSV byte buffer of `rows` x `cols` numeric fields into
// a pre-allocated float32 column-major-by-row (C-order [rows, cols]) array.
// Returns the number of rows parsed, or -1 on malformed input.
int64_t fd_parse_csv_f32(const char* buf, int64_t len, float* out,
                         int64_t rows, int64_t cols) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t r = 0;
  while (r < rows && p < end) {
    for (int64_t c = 0; c < cols; ++c) {
      // strtof skips leading whitespace; it stops at ',' or '\n'.
      char* next = nullptr;
      float v = strtof(p, &next);
      if (next == p) return -1;  // no progress: malformed field
      out[r * cols + c] = v;
      p = next;
      if (c + 1 < cols) {
        if (p < end && *p == ',') ++p;
        else return -1;
      }
    }
    // The row must END here: a ',' means more fields than the header
    // declared — reject rather than misalign every following row.
    if (p < end && *p != '\r' && *p != '\n') return -1;
    while (p < end && (*p == '\r' || *p == '\n')) ++p;
    ++r;
  }
  return r;
}

// Gather rows: out[i, :] = src[idx[i], :]  (the shuffle/epoch permutation).
void fd_gather_f32(const float* src, const int64_t* idx, float* out,
                   int64_t n_out, int64_t row_elems) {
  for (int64_t i = 0; i < n_out; ++i) {
    std::memcpy(out + i * row_elems, src + idx[i] * row_elems,
                sizeof(float) * (size_t)row_elems);
  }
}

// Pack a [batch, ...] minibatch from contiguous rows starting at `start`,
// applying an optional affine transform (scale/shift — fused min-max
// normalization so the feed doesn't need a second pass over the data).
void fd_pack_batch_f32(const float* src, float* out, int64_t start,
                       int64_t batch, int64_t row_elems, float scale,
                       float shift) {
  const float* s = src + start * row_elems;
  int64_t n = batch * row_elems;
  if (scale == 1.0f && shift == 0.0f) {
    std::memcpy(out, s, sizeof(float) * (size_t)n);
  } else {
    for (int64_t i = 0; i < n; ++i) out[i] = s[i] * scale + shift;
  }
}

// Fisher-Yates permutation with SplitMix64 — deterministic given seed.
void fd_permutation(int64_t* out, int64_t n, uint64_t seed) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  uint64_t x = seed + 0x9E3779B97F4A7C15ull;
  for (int64_t i = n - 1; i > 0; --i) {
    // splitmix64 step
    uint64_t z = (x += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    z = z ^ (z >> 31);
    int64_t j = (int64_t)(z % (uint64_t)(i + 1));
    int64_t t = out[i]; out[i] = out[j]; out[j] = t;
  }
}

// Column min/max in one pass (for MinMaxTransformer's fitted mode).
void fd_minmax_f32(const float* src, int64_t n, float* out_min,
                   float* out_max) {
  float lo = INFINITY, hi = -INFINITY;
  for (int64_t i = 0; i < n; ++i) {
    float v = src[i];
    if (v < lo) lo = v;
    if (v > hi) hi = v;
  }
  *out_min = lo;
  *out_max = hi;
}

}  // extern "C"
