// fastwire: native frame core for the serving front door's bin1 wire
// protocol (distkeras_tpu/serving/wire.py).
//
// The JSONL front door spends its request budget on readline() + per-line
// json.loads/json.dumps — the per-record serialization overhead DeepSpark
// (arXiv:1602.08191 §IV) names as its exchange-path scaling ceiling, and
// the control-plane bottleneck TensorFlow's design (arXiv:1605.08695 §4)
// is built to avoid. bin1 replaces lines with length-prefixed frames:
//
//   [u32 len (LE)] [u8 type] [u32 stream_id (LE)] [payload: len-5 bytes]
//
// The receive hot loop lives here behind ctypes (same pattern as
// fastdata.cpp: raw buffers shared with numpy, pure-Python struct
// fallback when the .so is absent or stale):
//
//   fw_scan_frames  — split a receive buffer into complete frames in one
//                     call (the batched-admission read path: every frame
//                     that arrived in one event-loop tick, one FFI hop;
//                     engaged for LARGE buffers — small ones scan faster
//                     in pure Python than one ctypes round trip costs);
//   fw_pack_token_frames — one contiguous buffer of TOK frames from many
//                     streams' token lists. The production send path
//                     (wire.FrameSink) stages raw payload bytes and
//                     frames them directly, so this serves wide int-list
//                     batch writers and the ctypes-vs-fallback parity
//                     suite.
//
// Build: make -C native   (produces libfastwire.so).

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t read_u32le(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

inline void write_u32le(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)(v & 0xff);
  p[1] = (uint8_t)((v >> 8) & 0xff);
  p[2] = (uint8_t)((v >> 16) & 0xff);
  p[3] = (uint8_t)((v >> 24) & 0xff);
}

}  // namespace

extern "C" {

// Scan `buf` for complete frames. For each complete frame i (< cap) the
// PAYLOAD location and the header fields are written to offsets[i] /
// lengths[i] / types[i] / streams[i]. Returns the number of complete
// frames found (0 when the buffer holds only a partial frame), and sets
// *consumed to the byte offset just past the last complete frame — the
// caller discards exactly that prefix and keeps the tail for the next
// read. Returns -1 on a corrupt header: a declared length below the
// 5-byte (type + stream) minimum, or above max_frame (an oversized — or
// desynchronized — peer must fail typed, never grow an unbounded buffer
// waiting for a frame that can't be trusted).
int64_t fw_scan_frames(const uint8_t* buf, int64_t len, int64_t max_frame,
                       int64_t* offsets, int64_t* lengths, uint8_t* types,
                       uint32_t* streams, int64_t cap, int64_t* consumed) {
  int64_t pos = 0;
  int64_t n = 0;
  *consumed = 0;
  while (n < cap && pos + 4 <= len) {
    uint32_t flen = read_u32le(buf + pos);
    if (flen < 5 || (int64_t)flen > max_frame) return -1;
    if (pos + 4 + (int64_t)flen > len) break;  // partial frame: stop clean
    types[n] = buf[pos + 4];
    streams[n] = read_u32le(buf + pos + 5);
    offsets[n] = pos + 9;
    lengths[n] = (int64_t)flen - 5;
    pos += 4 + (int64_t)flen;
    *consumed = pos;
    ++n;
  }
  return n;
}

// Pack n_streams TOK frames into `out` back to back: frame i carries
// tokens[offs[i] : offs[i+1]] (offs is a prefix-sum array of n_streams+1
// entries) for stream streams[i]. Returns bytes written. The caller
// sizes `out` as sum over i of (9 + 4 * count_i) — exact, no slack.
// `tok_type` is the TOK frame-type byte (passed in so the wire module
// owns the type registry in ONE place).
int64_t fw_pack_token_frames(const uint32_t* streams, const int64_t* offs,
                             const int32_t* tokens, int64_t n_streams,
                             uint8_t tok_type, uint8_t* out) {
  int64_t pos = 0;
  for (int64_t i = 0; i < n_streams; ++i) {
    int64_t count = offs[i + 1] - offs[i];
    uint32_t flen = (uint32_t)(5 + 4 * count);
    write_u32le(out + pos, flen);
    out[pos + 4] = tok_type;
    write_u32le(out + pos + 5, streams[i]);
    // Token ids are written little-endian; on LE hosts (every platform
    // this repo targets) that is a straight memcpy of the int32 array.
    std::memcpy(out + pos + 9, tokens + offs[i], (size_t)(4 * count));
    pos += 9 + 4 * count;
  }
  return pos;
}

}  // extern "C"
