"""Causal ring-attention load balance: contiguous vs striped layout.

The lock-step ring's wall clock is set by the BUSIEST device at each hop
(every hop ends in a ppermute barrier). This bench computes the EXACT
per-(device, hop) attention work for both layouts — pure mask
combinatorics, no hardware needed — and reports the makespan ratio, i.e.
how much faster the striped layout finishes the same causal attention.

Work model: one unit per (query, key) pair the mask admits. Contiguous
layout: device d owns rows [d*S/p, (d+1)*S/p); the hop visiting shard
``src`` is full (src < d), triangular (src == d), or empty (src > d).
Striped layout (stripe_shard): device d owns rows {d, d+p, ...}; every
hop is an inclusive or strict triangle of near-identical size.

Prints ONE JSON line. Exact by construction; the measured-numerics side
(striped output == dense causal, values and grads) is pinned in
tests/test_ring_flash.py.

  python benchmarks/ring_balance.py            # p=8, S=4096
  BENCH_SP=16 BENCH_SEQ=65536 python benchmarks/ring_balance.py
"""

from __future__ import annotations

import json
import os

import numpy as np


def hop_work(p: int, s_local: int, layout: str) -> np.ndarray:
    """work[d, h] = admitted (q, k) pairs on device d at hop h."""
    if layout not in ("contiguous", "striped"):
        raise ValueError(f"unknown layout {layout!r}")
    work = np.zeros((p, p), dtype=np.int64)
    tri_incl = s_local * (s_local + 1) // 2
    tri_strict = s_local * (s_local - 1) // 2
    full = s_local * s_local
    for d in range(p):
        for h in range(p):
            src = (d - h) % p
            if layout == "contiguous":
                work[d, h] = full if src < d else (tri_incl if src == d else 0)
            else:  # striped: q global = jq*p + d, k global = jk*p + src
                work[d, h] = tri_incl if src <= d else tri_strict
    return work


def main():
    p = int(os.environ.get("BENCH_SP", "8"))
    S = int(os.environ.get("BENCH_SEQ", "4096"))
    if S % p:
        raise SystemExit(f"BENCH_SEQ {S} not divisible by BENCH_SP {p}")
    s_local = S // p

    out = {"metric": "causal_ring_balance", "sp": p, "seq": S}
    makespans = {}
    for layout in ("contiguous", "striped"):
        w = hop_work(p, s_local, layout)
        # Lock-step: each hop costs its busiest device; total work is the
        # full causal triangle either way (exactness cross-check).
        makespan = int(w.max(axis=0).sum())
        total = int(w.sum())
        assert total == S * (S + 1) // 2, (layout, total)
        makespans[layout] = makespan
        out[layout] = {
            "makespan_units": makespan,
            "busiest_device_share": round(float(w.sum(axis=1).max() / total), 4),
            "idle_fraction": round(1.0 - total / (makespan * p), 4),
        }
    out["striped_speedup"] = round(
        makespans["contiguous"] / makespans["striped"], 4
    )
    # Limit p -> inf, s_local fixed: contiguous makespan -> p * full-block
    # hops on the last device vs striped -> p * half-block hops: ratio -> 2.
    print(json.dumps(out))


if __name__ == "__main__":
    main()
